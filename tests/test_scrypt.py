"""Scrypt PoW tests (BASELINE.json:11, eval config 5; SURVEY.md §7
stage 7): the device primitives (salsa20/8, BlockMix, ROMix) are pinned
against an independent pure-Python RFC 7914 reference, the batched
header hash against ``hashlib.scrypt`` (OpenSSL) bit-for-bit, the
miners against brute force, and a scrypt job runs end-to-end through
the cluster including the coordinator's mode-aware host verification.

N is 1024 (the Litecoin parameter) everywhere a miner runs; the
primitive tests also cover N=16 to exercise a second scan length.
"""

import hashlib
import struct

import numpy as np
import jax.numpy as jnp
import pytest

from tpuminter import chain
from tpuminter.ops import scrypt as sc
from tpuminter.protocol import PowMode, ProtocolError, Request, decode_msg, encode_msg
from tpuminter.worker import CpuMiner

# ---------------------------------------------------------------------------
# pure-Python RFC 7914 reference (r=1), validated against hashlib below
# ---------------------------------------------------------------------------


def _salsa_ref(inw):
    x = [int(v) for v in inw]

    def rot(a, b):
        a &= 0xFFFFFFFF
        return ((a << b) & 0xFFFFFFFF) | (a >> (32 - b))

    for _ in range(4):
        for tgt, a, b, r in sc._SALSA_STEPS:
            x[tgt] ^= rot(x[a] + x[b], r)
    return [(int(a) + b) & 0xFFFFFFFF for a, b in zip(inw, x)]


def _blockmix_ref(x32):
    b0, b1 = list(x32[:16]), list(x32[16:])
    y0 = _salsa_ref([int(a) ^ int(b) for a, b in zip(b1, b0)])
    y1 = _salsa_ref([a ^ int(b) for a, b in zip(y0, b1)])
    return y0 + y1


def _romix_ref(x32, n):
    v, x = [], [int(a) for a in x32]
    for _ in range(n):
        v.append(x)
        x = _blockmix_ref(x)
    for _ in range(n):
        x = _blockmix_ref([a ^ b for a, b in zip(x, v[x[16] % n])])
    return x


def test_python_reference_matches_openssl():
    """The pure-Python pipeline (PBKDF2 → ROMix → PBKDF2) reproduces
    hashlib.scrypt — so pinning the device primitives to it below is
    pinning them to OpenSSL."""
    msg = b"reference check" * 5
    for n in (2, 16, 1024):
        b = hashlib.pbkdf2_hmac("sha256", msg, msg, 1, 128)
        x = np.frombuffer(b, "<u4").astype(np.uint32)
        bp = np.array(_romix_ref(x, n), np.uint32).astype("<u4").tobytes()
        got = hashlib.pbkdf2_hmac("sha256", msg, bp, 1, 32)
        assert got == hashlib.scrypt(msg, salt=msg, n=n, r=1, p=1, dklen=32)


def test_salsa_and_blockmix_and_romix():
    rng = np.random.RandomState(7)
    x16 = rng.randint(0, 1 << 32, 16, dtype=np.uint32)
    assert [int(v) for v in np.asarray(sc.salsa20_8(jnp.asarray(x16)))] == _salsa_ref(x16)
    x32 = rng.randint(0, 1 << 32, 32, dtype=np.uint32)
    assert [int(v) for v in np.asarray(sc.block_mix(jnp.asarray(x32)))] == _blockmix_ref(x32)
    batch = rng.randint(0, 1 << 32, (3, 32), dtype=np.uint32)
    got = np.asarray(sc.romix(jnp.asarray(batch), 4))
    for i in range(3):
        assert [int(v) for v in got[i]] == _romix_ref(batch[i], 16)


@pytest.mark.parametrize("n_log2", [4, 10])
def test_scrypt_header_batch_matches_hashlib(n_log2):
    hdr = chain.GENESIS_HEADER.pack()
    hw = jnp.asarray(sc.header_to_words(hdr[:76]))
    nonces = np.array([0, 1, 12345, 0xFFFFFFFF], np.uint32)
    out = np.asarray(sc.scrypt_header_batch(hw, jnp.asarray(nonces), n_log2))
    for i, n in enumerate(nonces):
        msg = hdr[:76] + struct.pack("<I", int(n))
        want = hashlib.scrypt(msg, salt=msg, n=1 << n_log2, r=1, p=1, dklen=32)
        assert out[i].astype(">u4").tobytes() == want


def test_chain_scrypt_hash():
    hdr = chain.GENESIS_HEADER.pack()
    assert chain.scrypt_hash(hdr) == hashlib.scrypt(
        hdr, salt=hdr, n=1024, r=1, p=1, dklen=32
    )


# ---------------------------------------------------------------------------
# protocol plumbing
# ---------------------------------------------------------------------------


def test_scrypt_request_roundtrip():
    hdr = chain.GENESIS_HEADER.pack()
    req = Request(
        job_id=3, mode=PowMode.SCRYPT, lower=0, upper=100,
        header=hdr, target=1 << 240,
    )
    assert req.mode.targeted
    assert decode_msg(encode_msg(req)) == req


def test_scrypt_request_validation():
    with pytest.raises(ProtocolError):  # needs header+target like TARGET
        Request(job_id=1, mode=PowMode.SCRYPT, lower=0, upper=10)
    with pytest.raises(ProtocolError):  # u32 nonce space
        Request(job_id=1, mode=PowMode.SCRYPT, lower=0, upper=1 << 32,
                header=chain.GENESIS_HEADER.pack(), target=1)


# ---------------------------------------------------------------------------
# miners vs brute force (N=1024, small ranges)
# ---------------------------------------------------------------------------

HI = 199  # range [0, HI]


@pytest.fixture(scope="module")
def ground_truth():
    hdr = chain.GENESIS_HEADER.pack()
    prefix = hdr[:76]
    all_h = [
        (chain.hash_to_int(chain.scrypt_hash(prefix + struct.pack("<I", n))), n)
        for n in range(HI + 1)
    ]
    h_min, n_min = min(all_h)
    return hdr, all_h, h_min, n_min


def _drain(gen):
    result = None
    for item in gen:
        if item is not None:
            result = item
    return result


def test_cpu_miner_scrypt_finds_winner(ground_truth):
    hdr, all_h, h_min, n_min = ground_truth
    req = Request(job_id=1, mode=PowMode.SCRYPT, lower=0, upper=HI,
                  header=hdr, target=h_min)
    result = _drain(CpuMiner(batch=64).mine(req))
    assert result.found
    assert (result.nonce, result.hash_value) == (n_min, h_min)
    assert result.searched == n_min + 1  # first-winner early exit


def test_cpu_miner_scrypt_exhausted(ground_truth):
    hdr, all_h, h_min, n_min = ground_truth
    req = Request(job_id=1, mode=PowMode.SCRYPT, lower=0, upper=HI,
                  header=hdr, target=1)
    result = _drain(CpuMiner(batch=64).mine(req))
    assert not result.found
    assert (result.hash_value, result.nonce) == (h_min, n_min)
    assert result.searched == HI + 1


def test_jax_miner_scrypt_matches_cpu(ground_truth):
    from tpuminter.jax_worker import JaxMiner

    hdr, all_h, h_min, n_min = ground_truth
    miner = JaxMiner(scrypt_batch=64)
    req = Request(job_id=1, mode=PowMode.SCRYPT, lower=0, upper=HI,
                  header=hdr, target=h_min)
    result = _drain(miner.mine(req))
    assert result.found
    assert (result.nonce, result.hash_value) == (n_min, h_min)

    # partial chunk with ragged final batch, unbeatable target
    lo, hi = 37, 141
    want = min((h, n) for h, n in all_h if lo <= n <= hi)
    req = Request(job_id=1, mode=PowMode.SCRYPT, lower=lo, upper=hi,
                  header=hdr, target=1)
    result = _drain(miner.mine(req))
    assert not result.found
    assert (result.hash_value, result.nonce) == want


# ---------------------------------------------------------------------------
# rolled (extranonce) scrypt
# ---------------------------------------------------------------------------

NB = 5   # nonce_bits: 32-nonce segments
ENS = 4  # extranonce segments


@pytest.fixture(scope="module")
def rolled_truth():
    rng = np.random.RandomState(3)
    prefix, suffix = rng.bytes(41), rng.bytes(60)
    branch = [rng.bytes(32) for _ in range(2)]
    hdr = chain.GENESIS_HEADER.pack()
    cb = chain.CoinbaseTemplate(prefix, suffix, 4)
    all_h = []
    for en in range(ENS):
        p76 = chain.rolled_header(hdr, cb, branch, en).pack()[:76]
        for n in range(1 << NB):
            h = chain.hash_to_int(chain.scrypt_hash(p76 + struct.pack("<I", n)))
            all_h.append((h, (en << NB) | n))
    h_min, g_min = min(all_h)
    return prefix, suffix, branch, hdr, h_min, g_min


def _rolled_req(rt, target):
    prefix, suffix, branch, hdr, h_min, g_min = rt
    return Request(
        job_id=9, mode=PowMode.SCRYPT, lower=0, upper=(ENS << NB) - 1,
        header=hdr, target=target, coinbase_prefix=prefix,
        coinbase_suffix=suffix, extranonce_size=4, branch=tuple(branch),
        nonce_bits=NB,
    )


def test_cpu_miner_rolled_scrypt(rolled_truth):
    *_, h_min, g_min = rolled_truth
    result = _drain(CpuMiner(batch=32).mine(_rolled_req(rolled_truth, h_min)))
    assert result.found
    assert (result.nonce, result.hash_value) == (g_min, h_min)


def test_jax_miner_rolled_scrypt(rolled_truth):
    from tpuminter.jax_worker import JaxMiner

    *_, h_min, g_min = rolled_truth
    result = _drain(
        JaxMiner(scrypt_batch=32).mine(_rolled_req(rolled_truth, h_min))
    )
    assert result.found
    assert (result.nonce, result.hash_value) == (g_min, h_min)


# ---------------------------------------------------------------------------
# end-to-end through the cluster (eval config 5 shape)
# ---------------------------------------------------------------------------


def test_scrypt_job_end_to_end(ground_truth):
    import asyncio

    from tests.test_e2e import FAST, Cluster, run
    from tpuminter.client import submit

    hdr, all_h, h_min, n_min = ground_truth

    async def scenario():
        cluster = await Cluster.create(
            n_miners=2, chunk_size=64,
            miner_factory=lambda: CpuMiner(batch=32),
        )
        try:
            req = Request(job_id=5, mode=PowMode.SCRYPT, lower=0, upper=HI,
                          header=hdr, target=h_min)
            result = await submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            assert result.found
            assert (result.nonce, result.hash_value) == (n_min, h_min)
            # the coordinator's mode-aware host verification accepted it
            assert cluster.coord.stats["results_rejected"] == 0
        finally:
            await cluster.close()

    run(scenario())


def test_coordinator_rejects_forged_scrypt_result(ground_truth):
    """A worker claiming a scrypt win that is really only a double-SHA
    win must be caught by the mode-aware verifier."""
    from tpuminter.coordinator import Coordinator

    hdr, all_h, h_min, n_min = ground_truth
    from tpuminter.protocol import Result

    req = Request(job_id=1, mode=PowMode.SCRYPT, lower=0, upper=HI,
                  header=hdr, target=h_min)
    # forged: correct double-SHA hash of nonce 0, passed off as scrypt
    fake_h = chain.hash_to_int(chain.dsha256(hdr[:76] + struct.pack("<I", 0)))
    forged = Result(1, PowMode.SCRYPT, 0, fake_h, found=True)
    assert not Coordinator._verify_result(req, forged)
    honest = Result(1, PowMode.SCRYPT, n_min, h_min, found=True)
    assert Coordinator._verify_result(req, honest)


def test_romix_walk_uses_one_flat_row_gather_per_step():
    """Structural tripwire for the ROMix layout war (PERF.md): the walk
    body must read V with exactly ONE flat row-gather per scan step —
    the measured-optimal form (23 GB/s). The rejected layouts that each
    cost ~100x — ``take_along_axis`` on (N, B, 32), per-word element
    gathers on word-major V, plane-major element gathers (round 5:
    7 ms/step) — all trace to a different gather count or shape, so a
    silent regression to any of them fails here long before a bench
    run could catch it on hardware."""
    import jax

    from tpuminter.ops.scrypt import romix

    b, n_log2 = 256, 4
    jaxpr = jax.make_jaxpr(lambda x: romix(x, n_log2))(
        jnp.zeros((b, 32), jnp.uint32)
    )

    def walk_eqns(jx, out, in_scan=False):
        for eq in jx.eqns:
            out.append((eq, in_scan))
            inner_scan = in_scan or eq.primitive.name == "scan"
            for sub in eq.params.values():
                for item in sub if isinstance(sub, (tuple, list)) else (sub,):
                    if hasattr(item, "jaxpr"):
                        walk_eqns(item.jaxpr, out, inner_scan)
        return out

    every = walk_eqns(jaxpr.jaxpr, [])
    scans = [eq for eq, _ in every if eq.primitive.name == "scan"]
    assert len(scans) == 2, f"expected fill+walk scans, got {len(scans)}"
    # exactly one gather over ALL eqns (ADVICE r5 #1: counting only
    # inside scan bodies lets a hoisted gather — or a jaxlib change to
    # scan/pjit param structure that hides the bodies — pass silently),
    # and that one gather must live inside a scan (the walk's per-step
    # row read) and produce whole (B, 32) rows, the measured-optimal
    # flat layout (23 GB/s; every rejected layout differs here)
    gathers = [(eq, in_scan) for eq, in_scan in every
               if eq.primitive.name == "gather"]
    gather_shapes = [
        [tuple(v.aval.shape) for v in eq.outvars] for eq, _ in gathers
    ]
    assert gather_shapes == [[(b, 32)]], gather_shapes
    assert gathers[0][1], "the row gather was hoisted out of the walk scan"
    # pretty-print fallback so a jaxlib param-structure change that
    # breaks the structural walk above still fails loudly here instead
    # of silently walking zero eqns
    printed = str(jaxpr)
    assert printed.count(" gather[") == 1, printed.count(" gather[")
