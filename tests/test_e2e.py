"""End-to-end role tests (SURVEY.md §4 "Part B" strategy): coordinator +
miners + clients on localhost in one process; correctness is asserted
against brute-force ground truth; worker death mid-job must not lose or
corrupt results ("results must survive worker death")."""

import asyncio
import struct

import pytest

from tpuminter import chain
from tpuminter.client import submit
from tpuminter.coordinator import Coordinator
from tpuminter.lsp import Params
from tpuminter.protocol import PowMode, Request, Result
from tpuminter.worker import CpuMiner, run_miner

FAST = Params(
    epoch_limit=5,
    epoch_millis=50,
    window_size=32,
    max_backoff_interval=2,
    max_unacked_messages=32,
)


def run(coro, timeout=60.0):
    async def wrapped():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.run(wrapped())


def brute_min(data: bytes, lower: int, upper: int):
    best = min((chain.toy_hash(data, n), n) for n in range(lower, upper + 1))
    return best  # (hash, nonce)


class Cluster:
    """Coordinator + miner tasks wired up on localhost."""

    def __init__(self, coordinator):
        self.coord = coordinator
        self.serve_task = asyncio.ensure_future(coordinator.serve())
        self.miner_tasks = []

    @classmethod
    async def create(cls, n_miners=1, chunk_size=4096, miner_factory=CpuMiner,
                     **coord_kwargs):
        coord = await Coordinator.create(
            params=FAST, chunk_size=chunk_size, **coord_kwargs
        )
        self = cls(coord)
        for _ in range(n_miners):
            await self.add_miner(miner_factory())
        return self

    async def add_miner(self, miner):
        task = asyncio.ensure_future(
            run_miner("127.0.0.1", self.coord.port, miner, params=FAST)
        )
        self.miner_tasks.append(task)
        # let the Join land before work is submitted
        await asyncio.sleep(0.05)
        return task

    async def kill_miner(self, index):
        """Hard-kill a miner: cancel its task; no goodbye to the server.

        The coordinator only learns of the death through epoch-based
        liveness, exactly like a crashed reference miner process.
        """
        task = self.miner_tasks[index]
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass

    async def close(self):
        for t in self.miner_tasks:
            t.cancel()
        self.serve_task.cancel()
        await asyncio.gather(*self.miner_tasks, self.serve_task, return_exceptions=True)
        await self.coord.close()


# ---------------------------------------------------------------------------
# toy (MIN) mode — reference user story
# ---------------------------------------------------------------------------

def test_single_miner_min_mode_matches_brute_force():
    async def scenario():
        cluster = await Cluster.create(n_miners=1)
        try:
            req = Request(job_id=7, mode=PowMode.MIN, lower=0, upper=9999,
                          data=b"hello bitcoin")
            result = await submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            want_hash, want_nonce = brute_min(b"hello bitcoin", 0, 9999)
            assert result.job_id == 7
            assert (result.hash_value, result.nonce) == (want_hash, want_nonce)
            assert result.found
        finally:
            await cluster.close()

    run(scenario())


def test_three_miners_split_one_job():
    async def scenario():
        cluster = await Cluster.create(n_miners=3, chunk_size=1024)
        try:
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=20_000,
                          data=b"parallel")
            result = await submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            assert (result.hash_value, result.nonce) == brute_min(b"parallel", 0, 20_000)
            # the job really was split across chunks
            assert cluster.coord.stats["hashes"] == 20_001
        finally:
            await cluster.close()

    run(scenario())


def test_concurrent_clients_round_robin():
    async def scenario():
        cluster = await Cluster.create(n_miners=2, chunk_size=1024)
        try:
            reqs = [
                Request(job_id=i, mode=PowMode.MIN, lower=0, upper=8000,
                        data=f"job-{i}".encode())
                for i in range(3)
            ]
            results = await asyncio.gather(
                *(submit("127.0.0.1", cluster.coord.port, r, params=FAST) for r in reqs)
            )
            for i, result in enumerate(results):
                assert result.job_id == i
                want = brute_min(f"job-{i}".encode(), 0, 8000)
                assert (result.hash_value, result.nonce) == want
        finally:
            await cluster.close()

    run(scenario())


# ---------------------------------------------------------------------------
# worker death — the core recovery story
# ---------------------------------------------------------------------------

def test_miner_death_mid_job_requeues_and_completes():
    async def scenario():
        cluster = await Cluster.create(n_miners=2, chunk_size=1024)
        try:
            data = b"survive the death"
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=400_000, data=data)
            submit_task = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            )
            await asyncio.sleep(0.1)  # both miners are mid-chunk now
            await cluster.kill_miner(0)
            result = await submit_task
            assert (result.hash_value, result.nonce) == brute_min(data, 0, 400_000)
            assert cluster.coord.stats["chunks_requeued"] >= 1
        finally:
            await cluster.close()

    run(scenario())


def test_all_miners_die_then_new_miner_joins():
    async def scenario():
        cluster = await Cluster.create(n_miners=1, chunk_size=1024)
        try:
            data = b"late joiner saves the day"
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=150_000, data=data)
            submit_task = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            )
            await asyncio.sleep(0.1)
            await cluster.kill_miner(0)  # now zero miners; job must stall, not die
            await asyncio.sleep(0.5)     # past the death-detection horizon
            assert not submit_task.done()
            await cluster.add_miner(CpuMiner())  # elasticity: join mid-job
            result = await submit_task
            assert (result.hash_value, result.nonce) == brute_min(data, 0, 150_000)
        finally:
            await cluster.close()

    run(scenario())


def test_client_death_drops_job_and_coordinator_survives():
    async def scenario():
        cluster = await Cluster.create(n_miners=1, chunk_size=512)
        try:
            from tpuminter.lsp import LspClient
            from tpuminter.protocol import encode_msg

            doomed = await LspClient.connect("127.0.0.1", cluster.coord.port, FAST)
            doomed.write(encode_msg(
                Request(job_id=1, mode=PowMode.MIN, lower=0, upper=500_000,
                        data=b"abandoned")
            ))
            await asyncio.sleep(0.15)
            await doomed.close()  # client vanishes mid-job
            # coordinator must still serve a healthy client
            req = Request(job_id=2, mode=PowMode.MIN, lower=0, upper=2000, data=b"ok")
            result = await submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            assert (result.hash_value, result.nonce) == brute_min(b"ok", 0, 2000)
        finally:
            await cluster.close()

    run(scenario())


# ---------------------------------------------------------------------------
# TARGET mode — real Bitcoin semantics (capability delta, BASELINE.json:6-8)
# ---------------------------------------------------------------------------

def test_target_mode_finds_genesis_nonce():
    async def scenario():
        cluster = await Cluster.create(n_miners=2, chunk_size=256)
        try:
            genesis_nonce = chain.GENESIS_HEADER.nonce
            req = Request(
                job_id=1,
                mode=PowMode.TARGET,
                lower=genesis_nonce - 500,
                upper=genesis_nonce + 500,
                header=chain.GENESIS_HEADER.pack(),
                target=chain.bits_to_target(0x1D00FFFF),
            )
            result = await submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            assert result.found
            assert result.nonce == genesis_nonce
            digest = result.hash_value.to_bytes(32, "little")
            assert chain.hash_to_hex(digest) == chain.GENESIS_HASH_HEX
        finally:
            await cluster.close()

    run(scenario())


def test_target_mode_exhausted_reports_best_effort():
    async def scenario():
        cluster = await Cluster.create(n_miners=1, chunk_size=256)
        try:
            req = Request(
                job_id=1,
                mode=PowMode.TARGET,
                lower=0,
                upper=999,  # range with no winner at genesis difficulty
                header=chain.GENESIS_HEADER.pack(),
                target=chain.bits_to_target(0x1D00FFFF),
            )
            result = await submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            assert not result.found
            # best-effort minimum is still reported, and is reproducible
            prefix = chain.GENESIS_HEADER.pack()[:76]
            want = min(
                (chain.hash_to_int(chain.dsha256(prefix + struct.pack("<I", n))), n)
                for n in range(1000)
            )
            assert (result.hash_value, result.nonce) == want
        finally:
            await cluster.close()

    run(scenario())


def test_target_mode_early_exit_cancels_remaining_work():
    async def scenario():
        # easy target: ~1/16 of hashes win, so a hit lands in the first
        # chunks and the job must finish WITHOUT sweeping the huge range.
        cluster = await Cluster.create(n_miners=2, chunk_size=1024)
        try:
            easy_target = (1 << 252) - 1
            req = Request(
                job_id=1,
                mode=PowMode.TARGET,
                lower=0,
                upper=50_000_000,  # would take minutes to sweep on CPU
                header=chain.GENESIS_HEADER.pack(),
                target=easy_target,
            )
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST), 20.0
            )
            assert result.found
            prefix = chain.GENESIS_HEADER.pack()[:76]
            digest = chain.dsha256(prefix + struct.pack("<I", result.nonce))
            assert chain.hash_to_int(digest) == result.hash_value
            assert result.hash_value <= easy_target
            # early exit: nowhere near the full range was searched
            assert cluster.coord.stats["hashes"] < 1_000_000
        finally:
            await cluster.close()

    run(scenario())


def test_client_death_dispatches_other_clients_queued_jobs():
    """Regression (ADVICE.md r1 / VERDICT r2 weak #1a): when a client
    dies, its cancelled miners go idle — a second client's queued job
    must be dispatched to them immediately, not stall until an unrelated
    event arrives."""

    async def scenario():
        # one miner, chunk big enough that client A's whole job is a
        # single long-running chunk keeping the miner busy
        cluster = await Cluster.create(
            n_miners=1, chunk_size=4_000_000,
            miner_factory=lambda: CpuMiner(batch=512),
        )
        try:
            from tpuminter.lsp import LspClient
            from tpuminter.protocol import encode_msg

            doomed = await LspClient.connect("127.0.0.1", cluster.coord.port, FAST)
            doomed.write(encode_msg(
                Request(job_id=1, mode=PowMode.MIN, lower=0, upper=3_999_999,
                        data=b"doomed job")
            ))
            await asyncio.sleep(0.2)  # miner is now deep in A's chunk
            # client B's job queues behind A's in-flight chunk
            req_b = Request(job_id=2, mode=PowMode.MIN, lower=0, upper=2000,
                            data=b"waiting job")
            submit_b = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req_b, params=FAST)
            )
            await asyncio.sleep(0.2)
            assert not submit_b.done()
            await doomed.close()  # A dies; its chunk is cancelled
            # B's job must now complete with NO further cluster events
            result = await asyncio.wait_for(submit_b, 15.0)
            assert (result.hash_value, result.nonce) == brute_min(b"waiting job", 0, 2000)
        finally:
            await cluster.close()

    run(scenario())


def test_forged_found_result_is_rejected_and_liar_evicted():
    """Regression (ADVICE.md r1 / VERDICT r2 weak #1b): a worker claiming
    found=True with a hash no nonce produces must not finish the job; the
    chunk is requeued, and a worker that keeps forging is evicted
    (bounding the requeue ping-pong) so an honest miner's answer wins."""

    async def scenario():
        cluster = await Cluster.create(n_miners=0)
        try:
            from tpuminter.coordinator import MAX_REJECTIONS
            from tpuminter.lsp import LspClient
            from tpuminter.protocol import (
                Assign, Join, Result, Setup, decode_msg, encode_msg,
            )

            evil = await LspClient.connect("127.0.0.1", cluster.coord.port, FAST)
            evil.write(encode_msg(Join(backend="evil", lanes=1)))

            async def forge_forever():
                # answer every dispatch with an impossible winner
                modes = {}
                while True:
                    msg = decode_msg(await evil.read())
                    if isinstance(msg, Setup):
                        modes[msg.request.job_id] = msg.request.mode
                    elif isinstance(msg, Assign):
                        evil.write(encode_msg(Result(
                            msg.job_id, modes[msg.job_id], nonce=msg.lower,
                            hash_value=0, found=True, searched=1,
                            chunk_id=msg.chunk_id,
                        )))

            evil_task = asyncio.ensure_future(forge_forever())
            await asyncio.sleep(0.05)

            genesis_nonce = chain.GENESIS_HEADER.nonce
            req = Request(
                job_id=1,
                mode=PowMode.TARGET,
                lower=genesis_nonce - 500,
                upper=genesis_nonce + 500,
                header=chain.GENESIS_HEADER.pack(),
                target=chain.bits_to_target(0x1D00FFFF),
            )
            submit_task = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            )
            await asyncio.sleep(0.5)
            # forged winners must NOT have reached the client, and the
            # liar must have been evicted after MAX_REJECTIONS strikes
            assert not submit_task.done()
            assert cluster.coord.stats["results_rejected"] == MAX_REJECTIONS
            # an honest miner completes the requeued work
            await cluster.add_miner(CpuMiner())
            result = await asyncio.wait_for(submit_task, 30.0)
            assert result.found and result.nonce == genesis_nonce
            digest = result.hash_value.to_bytes(32, "little")
            assert chain.hash_to_hex(digest) == chain.GENESIS_HASH_HEX
            evil_task.cancel()
        finally:
            await cluster.close()

    run(scenario())


def test_refused_assign_requeues_and_resends_setup():
    """The template split's recovery seam (code-review r4): a worker
    whose template cache lost a live job Refuses the bare Assign; the
    coordinator requeues the chunk, re-ships the Setup, and the job
    still completes exactly — no wedged busy-forever miner."""

    async def scenario():
        cluster = await Cluster.create(n_miners=0, chunk_size=4096)
        from tpuminter.lsp import LspClient
        from tpuminter.protocol import (
            Assign, Join, Refuse, Result, Setup, decode_msg, encode_msg,
        )
        try:
            w = await LspClient.connect("127.0.0.1", cluster.coord.port, FAST)
            w.write(encode_msg(Join(backend="flaky", lanes=1)))
            setups = []

            async def act():
                refused = False
                templates = {}
                while True:
                    msg = decode_msg(await w.read())
                    if isinstance(msg, Setup):
                        setups.append(msg)
                        templates[msg.request.job_id] = msg.request
                    elif isinstance(msg, Assign):
                        if not refused:
                            refused = True
                            templates.pop(msg.job_id, None)  # "evicted"
                            w.write(encode_msg(Refuse(msg.job_id, msg.chunk_id)))
                            continue
                        t = templates.get(msg.job_id)
                        if t is None:
                            # a pipelined second Assign dispatched before
                            # our Refuse landed: refuse it too, exactly
                            # like the real worker role would
                            w.write(encode_msg(Refuse(msg.job_id, msg.chunk_id)))
                            continue
                        h, n = brute_min(t.data, msg.lower, msg.upper)
                        w.write(encode_msg(Result(
                            msg.job_id, t.mode, n, h, found=True,
                            searched=msg.upper - msg.lower + 1,
                            chunk_id=msg.chunk_id,
                        )))

            task = asyncio.ensure_future(act())
            req = Request(job_id=9, mode=PowMode.MIN, lower=0, upper=9999,
                          data=b"refuse me")
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST), 30.0
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"refuse me", 0, 9999
            )
            assert len(setups) >= 2  # the template really was re-shipped
            assert cluster.coord.stats["chunks_requeued"] >= 1
            task.cancel()
            await w.close()
        finally:
            await cluster.close()

    run(scenario())


def test_verify_result_rejects_out_of_range_nonce():
    """A real hash of a nonce OUTSIDE the dispatched range must fail
    host verification — else a malicious auditor could hunt beyond its
    sub-range for a framing hash, and a forger could poison the min
    fold with out-of-range values (code-review r4)."""
    req = Request(job_id=1, mode=PowMode.MIN, lower=100, upper=200, data=b"x")
    below = Result(1, PowMode.MIN, 50, chain.toy_hash(b"x", 50))
    above = Result(1, PowMode.MIN, 201, chain.toy_hash(b"x", 201))
    inside = Result(1, PowMode.MIN, 150, chain.toy_hash(b"x", 150))
    assert not Coordinator._verify_result(req, below)
    assert not Coordinator._verify_result(req, above)
    assert Coordinator._verify_result(req, inside)


def test_under_search_audit_catches_lazy_worker(monkeypatch):
    """VERDICT r3 missing #4: a worker whose Results verify (real hash
    of a real nonce) but that never actually searches its ranges is
    caught by a sampled re-mine on another worker, evicted, and its
    chunks re-mined — the client still gets the exact answer."""
    from tpuminter import coordinator as coord_mod

    # full-chunk audits make conviction deterministic; the fixture
    # guarantees no chunk's argmin sits at its own lower bound (what the
    # lazy worker always claims)
    monkeypatch.setattr(coord_mod, "AUDIT_SAMPLE", 1024)
    data = b"audit me"
    for lo in range(0, 8192, 1024):
        assert brute_min(data, lo, lo + 1023)[1] != lo, lo

    async def scenario():
        cluster = await Cluster.create(
            n_miners=0, chunk_size=1024, audit_rate=1.0, audit_seed=5,
        )
        from tpuminter.lsp import LspClient, LspConnectionLost
        from tpuminter.protocol import (
            Assign, Join, Result, Setup, decode_msg, encode_msg,
        )
        try:
            lazy = await LspClient.connect("127.0.0.1", cluster.coord.port, FAST)
            lazy.write(encode_msg(Join(backend="lazy", lanes=1)))

            async def be_lazy():
                # instantly answer every dispatch with the (verifiable!)
                # hash of the range's first nonce — never searching
                modes = {}
                try:
                    while True:
                        msg = decode_msg(await lazy.read())
                        if isinstance(msg, Setup):
                            modes[msg.request.job_id] = msg.request
                        elif isinstance(msg, Assign):
                            req = modes[msg.job_id]
                            lazy.write(encode_msg(Result(
                                msg.job_id, req.mode, nonce=msg.lower,
                                hash_value=chain.toy_hash(req.data, msg.lower),
                                found=True,
                                searched=msg.upper - msg.lower + 1,
                                chunk_id=msg.chunk_id,
                            )))
                except LspConnectionLost:
                    pass  # evicted, as expected

            lazy_task = asyncio.ensure_future(be_lazy())
            await asyncio.sleep(0.05)
            await cluster.add_miner(CpuMiner(batch=256))

            req = Request(job_id=3, mode=PowMode.MIN, lower=0, upper=8191,
                          data=data)
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST), 30.0
            )
            # exact answer despite the lazy worker's garbage folds
            assert (result.hash_value, result.nonce) == brute_min(data, 0, 8191)
            assert cluster.coord.stats["audits_failed"] >= 1
            assert cluster.coord.stats["audits_done"] >= 1
            # the lazy worker is gone from the fleet
            stats = cluster.coord.worker_stats()
            assert all(s["backend"] != "lazy" for s in stats.values())
            lazy_task.cancel()
        finally:
            await cluster.close()

    run(scenario())


def test_cancelled_miners_are_redispatched():
    """Regression: a Cancel that lands mid-chunk must return the miner to
    the idle pool (a cancelled worker sends no Result, so nothing else
    frees it). chunk_size > CpuMiner.batch so cancels interrupt mid-mine
    — the production default geometry."""

    async def scenario():
        cluster = await Cluster.create(
            n_miners=2, chunk_size=50_000,
            miner_factory=lambda: CpuMiner(batch=512),
        )
        try:
            easy_target = (1 << 252) - 1
            for round_no in range(3):
                req = Request(
                    job_id=round_no,
                    mode=PowMode.TARGET,
                    lower=0,
                    upper=10_000_000,
                    header=chain.GENESIS_HEADER.pack(),
                    target=easy_target,
                )
                result = await asyncio.wait_for(
                    submit("127.0.0.1", cluster.coord.port, req, params=FAST), 15.0
                )
                assert result.found
            # after three early-exited jobs both miners must still be
            # usable: a MIN job that needs the whole range completes
            req = Request(job_id=99, mode=PowMode.MIN, lower=0, upper=5000,
                          data=b"still alive")
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST), 15.0
            )
            assert (result.hash_value, result.nonce) == brute_min(b"still alive", 0, 5000)
        finally:
            await cluster.close()

    run(scenario())


def test_worker_stats_after_job():
    """Observability (SURVEY.md §5; VERDICT r2 #7): after a job, the
    coordinator's per-worker snapshots account for every verified hash,
    with rate and liveness fields populated."""

    async def scenario():
        cluster = await Cluster.create(
            n_miners=2, chunk_size=1000,
            miner_factory=lambda: CpuMiner(batch=256),
        )
        try:
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=7999,
                          data=b"stats")
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            assert result.found
            stats = cluster.coord.worker_stats()
            assert len(stats) == 2
            # MIN mode has no early exit: every nonce is searched exactly
            # once, and both workers got chunks (8 chunks, 2 workers)
            assert sum(s["hashes"] for s in stats.values()) == 8000
            for snap in stats.values():
                assert snap["backend"] == "cpu"
                assert snap["chunks_done"] >= 1
                assert snap["mhs"] > 0
                assert snap["idle_s"] is not None
                assert not snap["busy"]
        finally:
            await cluster.close()

    run(scenario())


def test_stats_endpoint_and_rate_line_mid_job(caplog):
    """VERDICT r3 weak #6: the aggregate observability surface — the
    HTTP JSON stats endpoint answers mid-job with busy workers and live
    counters, and the periodic rate line fires while work flows."""
    import json as _json
    import logging as _logging

    async def scenario():
        cluster = await Cluster.create(
            n_miners=2, chunk_size=1024, stats_interval=0.1,
            miner_factory=lambda: CpuMiner(batch=256),
        )
        try:
            port = await cluster.coord.start_stats_server(0)
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=500_000,
                          data=b"observe me")
            job = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            )
            await asyncio.sleep(0.3)  # mid-job
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(b"GET / HTTP/1.0\r\n\r\n")
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), 5.0)
            writer.close()
            head, _, body = raw.partition(b"\r\n\r\n")
            assert head.startswith(b"HTTP/1.0 200")
            snap = _json.loads(body)
            assert snap["jobs_active"] >= 1
            assert snap["stats"]["hashes"] >= 0
            assert len(snap["workers"]) == 2
            assert any(w["busy"] for w in snap["workers"].values())
            result = await asyncio.wait_for(job, 60.0)
            assert (result.hash_value, result.nonce) == brute_min(
                b"observe me", 0, 500_000
            )
        finally:
            await cluster.close()

    with caplog.at_level(_logging.INFO, logger="tpuminter.coordinator"):
        run(scenario())
    assert any("rate:" in rec.message for rec in caplog.records)


def test_chaos_drops_deaths_and_concurrent_clients():
    """Robustness under combined failure modes (SURVEY.md §4's
    drops+epochs long-running tests): 10% loss + 10% duplication + 10%
    reordering in BOTH directions at the coordinator's transport seam,
    a miner hard-killed mid-flight, a replacement joining mid-flight —
    three concurrent clients must all still get exact answers, with
    every retransmission and requeue happening under the storm."""

    async def scenario():
        cluster = await Cluster.create(
            n_miners=3, chunk_size=500,
            miner_factory=lambda: CpuMiner(batch=128),
        )
        try:
            endpoint = cluster.coord._server.endpoint
            endpoint.set_fault_rates(drop=0.10, dup=0.10, reorder=0.10)
            endpoint.reorder_delay = 0.02

            async def one_client(jid, data, upper):
                req = Request(job_id=jid, mode=PowMode.MIN, lower=0,
                              upper=upper, data=data)
                return await submit(
                    "127.0.0.1", cluster.coord.port, req, params=FAST
                )

            jobs = [
                asyncio.ensure_future(one_client(1, b"chaos-a", 200_000)),
                asyncio.ensure_future(one_client(2, b"chaos-b", 150_000)),
                asyncio.ensure_future(one_client(3, b"chaos-c", 120_000)),
            ]
            await asyncio.sleep(0.3)          # jobs in flight...
            # the kill must hit a LIVE cluster or this hollows out into
            # a plain concurrency test (r3 review)
            assert not all(j.done() for j in jobs), "jobs finished too fast"
            await cluster.kill_miner(0)       # one miner crashes
            await cluster.add_miner(CpuMiner(batch=128))  # elastic rejoin
            results = await asyncio.wait_for(asyncio.gather(*jobs), 90.0)
            for result, (data, upper) in zip(
                results,
                [(b"chaos-a", 200_000), (b"chaos-b", 150_000), (b"chaos-c", 120_000)],
            ):
                assert (result.hash_value, result.nonce) == brute_min(
                    data, 0, upper
                ), data
        finally:
            await cluster.close()

    run(scenario(), timeout=120.0)


def test_mixed_fleet_heterogeneous_backends():
    """A job split across cpu + jax miners (different backends, one
    interface — BASELINE.json:5's mixed-fleet story): the fold across
    heterogeneous workers must still be exact."""
    from tpuminter.jax_worker import JaxMiner

    async def scenario():
        cluster = await Cluster.create(n_miners=0, chunk_size=1500)
        await cluster.add_miner(CpuMiner(batch=256))
        await cluster.add_miner(JaxMiner(batch=1 << 12, lanes=1))
        try:
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=11_999,
                          data=b"mixed fleet")
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"mixed fleet", 0, 11_999
            )
            stats = cluster.coord.worker_stats()
            assert sorted(s["backend"] for s in stats.values()) == ["cpu", "jax"]
            # both backends did verified work
            assert all(s["hashes"] > 0 for s in stats.values())
        finally:
            await cluster.close()

    run(scenario())


def test_pod_worker_death_requeues_to_cpu():
    """A whole-slice worker dying is just a (big) worker death: its
    chunk requeues and a surviving CPU miner completes the job — the
    slice-level failure-domain story (SURVEY.md §5)."""
    import jax as _jax

    if len(_jax.devices()) < 8:
        pytest.skip("needs the fake 8-device CPU mesh")
    from tpuminter.parallel import make_mesh
    from tpuminter.pod_worker import PodMiner

    async def scenario():
        mesh = make_mesh(_jax.devices()[:8])
        cluster = await Cluster.create(n_miners=0, chunk_size=2000)
        await cluster.add_miner(
            PodMiner(mesh=mesh, slab_per_device=128, n_slabs=2, kernel="jnp")
        )
        await cluster.add_miner(CpuMiner(batch=256))
        try:
            # large enough that a warm pod can't finish before the kill
            # lands (a 10k job completed in <0.2 s once JAX was warm and
            # turned this into a flake)
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=149_999,
                          data=b"pod dies")
            job = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            )
            # kill the pod the moment it demonstrably holds a chunk
            for _ in range(2000):
                stats = cluster.coord.worker_stats()
                if any(s["backend"] == "pod" and s["busy"]
                       for s in stats.values()):
                    break
                await asyncio.sleep(0.005)
            else:
                raise AssertionError("pod never got a chunk")
            assert not job.done(), "job finished before the kill landed"
            await cluster.kill_miner(0)  # the whole "slice" goes down
            result = await asyncio.wait_for(job, 60.0)
            assert (result.hash_value, result.nonce) == brute_min(
                b"pod dies", 0, 149_999
            )
            # the death really cost a chunk (not an idle-miner kill)
            assert cluster.coord.stats["chunks_requeued"] >= 1
        finally:
            await cluster.close()

    run(scenario())


def test_straggler_hedging_rescues_slow_chunk():
    """Opt-in speculative backup dispatch: a chunk stuck on a stalled
    miner is duplicated onto idle capacity once nothing else is queued,
    the backup's verified Result wins, and the straggler is released
    with a Cancel — the job completes exactly despite a worker that
    never answers."""
    import time as _time

    from tpuminter.worker import Miner

    class StallMiner(Miner):
        backend = "cpu"
        lanes = 1

        def mine(self, request):
            while True:
                _time.sleep(0.05)  # forever "mining", never a Result
                yield None

    async def scenario():
        cluster = await Cluster.create(
            n_miners=0, chunk_size=3000, hedge_after=0.3
        )
        await cluster.add_miner(StallMiner())       # gets chunk [0, 2999]
        await cluster.add_miner(CpuMiner(batch=256))
        try:
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=5999,
                          data=b"hedge me")
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST),
                30.0,
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"hedge me", 0, 5999
            )
            assert cluster.coord.stats["chunks_hedged"] >= 1
        finally:
            await cluster.close()

    run(scenario())


def test_hedging_disabled_by_default_no_duplicates():
    """Without hedge_after, accounting stays exact (no duplicated
    work): the original semantics are untouched by the feature."""

    async def scenario():
        cluster = await Cluster.create(n_miners=2, chunk_size=1024)
        try:
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=20_000,
                          data=b"no hedge")
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"no hedge", 0, 20_000
            )
            assert cluster.coord.stats["hashes"] == 20_001
            assert cluster.coord.stats["chunks_hedged"] == 0
        finally:
            await cluster.close()

    run(scenario())


def test_one_client_connection_many_jobs():
    """A single LSP connection may submit several Requests; each job's
    final Result echoes the client's own job_id so answers can arrive
    in any order and still be matched (the reference's client sends one
    request, but the protocol — and our scheduler — supports many)."""
    from tpuminter.lsp import LspClient
    from tpuminter.protocol import Result as ResultMsg
    from tpuminter.protocol import decode_msg, encode_msg

    async def scenario():
        cluster = await Cluster.create(n_miners=2, chunk_size=1024)
        try:
            conn = await LspClient.connect(
                "127.0.0.1", cluster.coord.port, FAST
            )
            jobs = {
                11: (b"multi-a", 9_000),
                22: (b"multi-b", 4_000),
                33: (b"multi-c", 6_500),
            }
            for jid, (data, upper) in jobs.items():
                conn.write(encode_msg(Request(
                    job_id=jid, mode=PowMode.MIN, lower=0, upper=upper,
                    data=data,
                )))
            got = {}
            while len(got) < len(jobs):
                msg = decode_msg(await conn.read())
                assert isinstance(msg, ResultMsg)
                got[msg.job_id] = msg
            await conn.close()
            for jid, (data, upper) in jobs.items():
                want = brute_min(data, 0, upper)
                assert (got[jid].hash_value, got[jid].nonce) == want, jid
        finally:
            await cluster.close()

    run(scenario())


# ---------------------------------------------------------------------------
# pipelined-worker dispatch granularity (Join.span)
# ---------------------------------------------------------------------------

def test_span_hint_sizes_chunks_to_multiple_spans():
    """A worker advertising a pipeline span gets chunks covering
    SPANS_PER_DISPATCH spans, so its slab pipeline never drains at a
    chunk boundary (PERF.md: single-span dispatch measured 9% slower);
    a lanes=1 budget of chunk_size=600 would otherwise carve 600-nonce
    crumbs for this device-class miner."""
    sizes = []

    class SpanMiner(CpuMiner):
        span = 5_000

        def mine(self, request):
            sizes.append(request.upper - request.lower + 1)
            yield from super().mine(request)

    async def scenario():
        cluster = await Cluster.create(
            n_miners=1, chunk_size=600, miner_factory=SpanMiner
        )
        try:
            req = Request(job_id=3, mode=PowMode.MIN, lower=0, upper=99_999,
                          data=b"span hint")
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            want_hash, want_nonce = brute_min(b"span hint", 0, 99_999)
            assert (result.hash_value, result.nonce) == (want_hash, want_nonce)
        finally:
            await cluster.close()

    run(scenario())
    from tpuminter.coordinator import SPANS_PER_DISPATCH

    assert sizes, "miner never received a chunk"
    assert sum(sizes) == 100_000
    assert min(sizes) >= SPANS_PER_DISPATCH * SpanMiner.span


def test_huge_span_hint_cannot_monopolize_a_job():
    """lanes/span are unvalidated wire hints: a worker advertising an
    absurd span still never gets more than half a job in one dispatch,
    so a second worker can always participate (and a hedge backup's
    size class can always cover any chunk)."""
    sizes = []

    class GreedyMiner(CpuMiner):
        span = 1 << 31

        def mine(self, request):
            sizes.append(request.upper - request.lower + 1)
            yield from super().mine(request)

    async def scenario():
        cluster = await Cluster.create(
            n_miners=1, chunk_size=600, miner_factory=GreedyMiner
        )
        try:
            req = Request(job_id=4, mode=PowMode.MIN, lower=0, upper=99_999,
                          data=b"greedy")
            result = await submit(
                "127.0.0.1", cluster.coord.port, req, params=FAST
            )
            want = brute_min(b"greedy", 0, 99_999)
            assert (result.hash_value, result.nonce) == want
        finally:
            await cluster.close()

    run(scenario())
    assert len(sizes) >= 2
    assert max(sizes) <= 50_000
    assert sum(sizes) == 100_000


def test_client_sees_disconnected_when_coordinator_dies_mid_job():
    """Reference UX (SURVEY.md §3.1): a client blocked on its Result
    must learn of coordinator death through epoch liveness — submit
    raises LspConnectionLost (the CLI prints ``Disconnected`` on it,
    client.py:148) rather than hanging forever on a queued job."""
    from tpuminter.lsp import LspConnectionLost

    async def scenario():
        cluster = await Cluster.create(n_miners=0)  # job queues forever
        job = asyncio.ensure_future(submit(
            "127.0.0.1", cluster.coord.port,
            Request(job_id=9, mode=PowMode.MIN, lower=0, upper=10**6,
                    data=b"orphaned job"),
            params=FAST,
        ))
        closed = False
        try:
            await asyncio.sleep(0.3)  # connect + submit land
            assert not job.done(), (
                f"submit finished early: "
                f"{job.exception() if not job.cancelled() else 'cancelled'}"
            )
            await cluster.close()  # coordinator dies, no goodbye
            closed = True
            with pytest.raises(LspConnectionLost):
                await asyncio.wait_for(job, timeout=30)
        finally:
            if not closed:
                await cluster.close()
            if not job.done():
                job.cancel()
            await asyncio.gather(job, return_exceptions=True)

    run(scenario())


# ---------------------------------------------------------------------------
# reference-default LSP params (VERDICT r5 next #6: the last true
# coverage hole — every scenario above runs on FAST millisecond epochs)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_reference_default_params_survive_miner_death():
    """One full scenario on ``Params()`` DEFAULTS (epoch_limit 5,
    epoch_millis 2000, window_size 1 — the canonical reference
    vintage): coordinator + 2 miners + client, one miner hard-killed
    mid-job. Death detection takes 5 × 2 s of real time here, which is
    exactly the point — the window-1, seconds-scale regime is a
    different operating point of the same machine (stop-and-wait sends,
    heartbeat pacing, loss horizon) and nothing above exercises it."""

    async def scenario():
        defaults = Params()
        coord = await Coordinator.create(params=defaults)
        serve = asyncio.ensure_future(coord.serve())
        miners = [
            asyncio.ensure_future(run_miner(
                "127.0.0.1", coord.port, CpuMiner(batch=2048),
                params=defaults,
            ))
            for _ in range(2)
        ]
        try:
            await asyncio.sleep(1.0)  # both Joins land
            assert len(coord.worker_stats()) == 2
            data = b"reference defaults"
            req = Request(job_id=1, mode=PowMode.MIN, lower=0,
                          upper=600_000, data=data)
            job = asyncio.ensure_future(submit(
                "127.0.0.1", coord.port, req, params=defaults
            ))
            # kill a miner once BOTH demonstrably hold chunks (so the
            # victim's death provably costs an in-flight chunk)
            for _ in range(400):
                stats = coord.worker_stats()
                if len(stats) == 2 and all(
                    s["busy"] for s in stats.values()
                ):
                    break
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("miners never both went busy")
            assert not job.done()
            victim = miners[0]
            victim.cancel()
            await asyncio.gather(victim, return_exceptions=True)
            # 10 s loss horizon + remaining mining, with slack for the
            # window-1 message pacing
            result = await asyncio.wait_for(job, 120.0)
            assert (result.hash_value, result.nonce) == brute_min(
                data, 0, 600_000
            )
            assert coord.stats["chunks_requeued"] >= 1
        finally:
            for m in miners:
                m.cancel()
            serve.cancel()
            await asyncio.gather(*miners, serve, return_exceptions=True)
            await coord.close()

    run(scenario(), timeout=180.0)


# ---------------------------------------------------------------------------
# long-lived coordinator soak (VERDICT r4 missing #3)
# ---------------------------------------------------------------------------

def test_coordinator_soak_50_jobs_drains_all_bookkeeping():
    """One coordinator through 50 mixed-mode jobs with every optional
    subsystem on at once — audits at rate 1.0, hedging armed, a lying
    worker evicted mid-run, a healthy worker hard-killed mid-run — then
    prove the process could run forever: every internal map (_jobs,
    _rotation, _audit_queue, _audits, per-miner chunks, per-client job
    sets) drains to empty and stats_snapshot reports zero queue depth.
    The reference's coordinator runs indefinitely; seconds-long
    scenarios alone cannot catch bookkeeping that leaks per job."""
    from tpuminter.lsp import LspClient, LspConnectionLost
    from tpuminter.protocol import Assign, Join, Setup, decode_msg, encode_msg

    data = b"soak job payload"
    gn = chain.GENESIS_HEADER.nonce
    diff1 = chain.bits_to_target(0x1D00FFFF)
    hdr = chain.GENESIS_HEADER.pack()

    def make_requests():
        reqs = []
        for i in range(50):
            jid = 100 + i
            kind = i % 10
            if kind == 8:  # TARGET that finds the genesis winner
                reqs.append((jid, Request(
                    job_id=jid, mode=PowMode.TARGET, lower=gn - 1200,
                    upper=gn + 800, header=hdr, target=diff1,
                ), ("target-found",)))
            elif kind == 9:  # TARGET exhausted (best-effort min)
                reqs.append((jid, Request(
                    job_id=jid, mode=PowMode.TARGET, lower=i * 100,
                    upper=i * 100 + 1499, header=hdr, target=1,
                ), ("target-miss",)))
            elif kind == 7:  # SCRYPT exhausted (memory-hard: slow). The
                # kill batch's scrypt job is bigger so the best-effort
                # chaos kill below has slow chunks to land on (the
                # PROVABLE requeue attribution is the separate
                # mute-worker phase after the soak loop).
                reqs.append((jid, Request(
                    job_id=jid, mode=PowMode.SCRYPT, lower=0,
                    upper=1199 if i == 27 else 59 + i,
                    header=hdr, target=1,
                ), ("scrypt",)))
            else:  # MIN with per-job payload and varying ranges
                lo = 37 * i
                reqs.append((jid, Request(
                    job_id=jid, mode=PowMode.MIN, lower=lo,
                    upper=lo + 2000 + 100 * (i % 5), data=data + bytes([i]),
                ), ("min",)))
        return reqs

    async def scenario():
        # batch=64 keeps yield (= cancellation) points dense: the mid-
        # soak hard-kill below must interrupt a chunk MID-COMPUTE, and a
        # miner that crunches a whole chunk in one synchronous step can
        # slip its Result out before task cancellation is delivered
        cluster = await Cluster.create(
            n_miners=3, chunk_size=512, audit_rate=1.0, audit_seed=11,
            hedge_after=0.25, miner_factory=lambda: CpuMiner(batch=64),
        )
        coord = cluster.coord
        try:
            # a verifiable-but-lying worker (the lazy pattern): answers
            # every MIN dispatch instantly with its range's first nonce
            liar = await LspClient.connect("127.0.0.1", coord.port, FAST)
            liar.write(encode_msg(Join(backend="liar", lanes=1)))

            async def be_lazy():
                modes = {}
                try:
                    while True:
                        msg = decode_msg(await liar.read())
                        if isinstance(msg, Setup):
                            modes[msg.request.job_id] = msg.request
                        elif isinstance(msg, Assign):
                            req = modes[msg.job_id]
                            if req.mode != PowMode.MIN:
                                continue  # stall non-MIN: hedging covers
                            liar.write(encode_msg(Result(
                                msg.job_id, req.mode, nonce=msg.lower,
                                hash_value=chain.toy_hash(req.data, msg.lower),
                                found=True,
                                searched=msg.upper - msg.lower + 1,
                                chunk_id=msg.chunk_id,
                            )))
                except LspConnectionLost:
                    pass  # evicted, as expected

            liar_task = asyncio.ensure_future(be_lazy())
            await asyncio.sleep(0.05)

            def true_result(req, msg):
                """Brute-force the exact answer for a small assign —
                the mute worker stays in good standing on audits."""
                lo, hi = msg.lower, msg.upper
                if req.mode == PowMode.MIN:
                    h, n = brute_min(req.data, lo, hi)
                    return Result(msg.job_id, req.mode, n, h, found=True,
                                  searched=hi - lo + 1, chunk_id=msg.chunk_id)
                fn = (chain.scrypt_hash if req.mode == PowMode.SCRYPT
                      else chain.dsha256)
                pre = req.header[:76]
                best = None
                for n in range(lo, hi + 1):
                    h = chain.hash_to_int(fn(pre + struct.pack("<I", n)))
                    if h <= req.target:
                        return Result(msg.job_id, req.mode, n, h, found=True,
                                      searched=n - lo + 1,
                                      chunk_id=msg.chunk_id)
                    if best is None or (h, n) < best:
                        best = (h, n)
                return Result(msg.job_id, req.mode, best[1], best[0],
                              found=False, searched=hi - lo + 1,
                              chunk_id=msg.chunk_id)

            async def start_mute():
                mute = await LspClient.connect(
                    "127.0.0.1", coord.port, FAST
                )
                mute.write(encode_msg(Join(backend="mute", lanes=1)))

                async def run_mute():
                    setups = {}
                    try:
                        while True:
                            msg = decode_msg(await mute.read())
                            if isinstance(msg, Setup):
                                setups[msg.request.job_id] = msg.request
                            elif isinstance(msg, Assign):
                                if msg.upper - msg.lower + 1 >= 400:
                                    continue  # stall the real job chunk
                                mute.write(encode_msg(
                                    true_result(setups[msg.job_id], msg)
                                ))
                    except LspConnectionLost:
                        pass

                task = asyncio.ensure_future(run_mute())
                await asyncio.sleep(0.05)
                return mute, task

            reqs = make_requests()
            results = {}
            for batch_start in range(0, len(reqs), 10):
                batch = reqs[batch_start:batch_start + 10]
                futures = [
                    asyncio.ensure_future(
                        submit("127.0.0.1", coord.port, req, params=FAST)
                    )
                    for _, req, _ in batch
                ]
                if batch_start == 20:
                    # hard-kill the whole cpu fleet mid-batch with
                    # simultaneous cancels (sequential kills let a
                    # victim finish a chunk during close-drain) — the
                    # chaos ingredient; requeue ATTRIBUTION has its own
                    # deterministic phase after the soak loop
                    victims = [t for t in cluster.miner_tasks
                               if not t.done()]
                    for t in victims:
                        t.cancel()
                    await asyncio.gather(*victims, return_exceptions=True)
                    for _ in range(3):
                        await cluster.add_miner(CpuMiner(batch=64))
                outs = await asyncio.gather(*futures)
                for (jid, _, _), out in zip(batch, outs):
                    results[jid] = out

            # deterministic death-requeue attribution, as its own phase
            # (during the soak batches, audit-first dispatch starves a
            # late joiner of job chunks ~20% of runs): a MUTE worker
            # that answers small assigns correctly (audits are <=
            # AUDIT_SAMPLE = 256 nonces, so it stays in good standing)
            # but STALLS any >= 400-nonce job chunk — held inflight
            # with no completion race possible. Closing its connection
            # must route that chunk through the COUNTED requeue path,
            # and the job then completes exact on the survivors.
            # Hedging is parked for this phase: the queue drains in
            # ~0.2 s (toy chunks are ~1 ms), after which a hedge copy
            # of the stalled chunk would win the race against epoch
            # loss and release it through the UNCOUNTED settle path —
            # the hedging subsystem doing its job, but not the path
            # under test here.
            coord._hedge_after = 1e9  # ticker re-reads it each cycle
            mute, mute_task = await start_mute()
            attribution = Request(
                job_id=999, mode=PowMode.MIN, lower=0, upper=511_999,
                data=b"requeue attribution",
            )
            fut = asyncio.ensure_future(submit(
                "127.0.0.1", coord.port, attribution, params=FAST
            ))
            for _ in range(3000):
                if any(
                    m.backend == "mute" and cid not in coord._audits
                    for m in coord._miners.values()
                    for cid in m.chunks
                ):
                    break
                await asyncio.sleep(0.01)
            else:
                dump = {
                    conn: (m.backend, dict(m.chunks),
                           sorted(c for c in m.chunks
                                  if c in coord._audits))
                    for conn, m in coord._miners.items()
                }
                raise AssertionError(
                    f"mute never stalled a job chunk; miners={dump} "
                    f"job999_done={fut.done()} "
                    f"snap={coord.stats_snapshot()['jobs_active']}"
                )
            requeued_before = coord.stats["chunks_requeued"]
            await mute.close(drain_timeout=0.05)
            mute_task.cancel()
            await asyncio.gather(mute_task, return_exceptions=True)
            out999 = await asyncio.wait_for(fut, 90)
            assert (out999.hash_value, out999.nonce) == brute_min(
                attribution.data, 0, 511_999
            )
            assert coord.stats["chunks_requeued"] > requeued_before

            # every job's answer is exact despite liar/death/hedges
            for jid, req, tag in reqs:
                out = results[jid]
                assert out.job_id == jid
                if tag[0] == "min":
                    want = brute_min(req.data, req.lower, req.upper)
                    assert (out.hash_value, out.nonce) == want, (jid, tag)
                    assert out.found
                elif tag[0] == "target-found":
                    assert out.found and out.nonce == gn
                elif tag[0] == "target-miss":
                    assert not out.found
                    want = min(
                        (chain.hash_to_int(chain.dsha256(
                            hdr[:76] + struct.pack("<I", n))), n)
                        for n in range(req.lower, req.upper + 1)
                    )
                    assert (out.hash_value, out.nonce) == want, jid
                else:  # scrypt exhausted: exact min of the range
                    want = min(
                        (chain.hash_to_int(chain.scrypt_hash(
                            hdr[:76] + struct.pack("<I", n))), n)
                        for n in range(req.lower, req.upper + 1)
                    )
                    assert not out.found
                    assert (out.hash_value, out.nonce) == want, jid

            # the liar was caught and evicted along the way
            assert coord.stats["audits_failed"] >= 1
            assert all(
                s["backend"] != "liar" for s in coord.worker_stats().values()
            )
            assert coord.stats["jobs_done"] >= 50

            # drain: audits may outlive their jobs by design; give the
            # fleet a bounded window to settle every trailing audit
            deadline = asyncio.get_event_loop().time() + 20.0
            while asyncio.get_event_loop().time() < deadline:
                snap = coord.stats_snapshot()
                busy = any(
                    w["busy"] for w in snap["workers"].values()
                )
                if (
                    snap["jobs_active"] == 0
                    and snap["chunks_queued"] == 0
                    and snap["audits_queued"] == 0
                    and not busy
                ):
                    break
                await asyncio.sleep(0.1)

            # the leak-free guarantee, on the raw internals
            assert coord._jobs == {}, coord._jobs
            assert not coord._rotation, coord._rotation
            assert not coord._audit_queue, coord._audit_queue
            assert coord._audits == {}, coord._audits
            for m in coord._miners.values():
                assert not m.chunks, (m.conn_id, dict(m.chunks))
            assert not any(coord._clients.values()), coord._clients
            snap = coord.stats_snapshot()
            assert snap["jobs_active"] == 0
            assert snap["chunks_queued"] == 0
            assert snap["audits_queued"] == 0
            liar_task.cancel()
            await asyncio.gather(liar_task, return_exceptions=True)
        finally:
            await cluster.close()

    run(scenario(), timeout=240.0)


# ---------------------------------------------------------------------------
# dispatch budget arithmetic (unit-level: the span-alignment rules)
# ---------------------------------------------------------------------------

def test_budget_span_alignment_and_caps():
    """ADVICE r4: chunk budgets for pipelined miners must be whole
    multiples of the worker's span (a chunk ending mid-span refills the
    pod pipeline once per chunk), including AFTER the half-job cap; the
    scrypt floor loses to the half-job cap on tiny jobs by design."""
    from tpuminter.coordinator import (
        SCRYPT_MIN_CHUNK, SPANS_PER_DISPATCH, _Job, _MinerState,
    )

    async def scenario():
        coord = await Coordinator.create(params=FAST, chunk_size=4096)
        try:
            def job(mode, lower, upper):
                kw = (dict(data=b"x") if mode == PowMode.MIN else
                      dict(header=chain.GENESIS_HEADER.pack(), target=1))
                return _Job(job_id=1, client_conn=1, client_job_id=1,
                            request=Request(job_id=1, mode=mode,
                                            lower=lower, upper=upper,
                                            **kw))

            def miner(lanes=1, span=0):
                return _MinerState(conn_id=9, backend="t", lanes=lanes,
                                   span=span)

            big = job(PowMode.MIN, 0, (1 << 32) - 1)

            # pipelined miner: budget is a whole number of spans and at
            # least SPANS_PER_DISPATCH of them
            m = miner(lanes=7, span=1000)
            b = coord._budget(m, big)
            assert b % 1000 == 0
            assert b >= SPANS_PER_DISPATCH * 1000

            # chunk_size*lanes dominating must still be span-aligned
            m2 = miner(lanes=1000, span=999)  # 4096*1000 not a multiple
            b2 = coord._budget(m2, big)
            assert b2 % 999 == 0 and b2 > 0

            # the half-job cap can land mid-span; the re-round restores
            # alignment while at least one whole span fits
            small = job(PowMode.MIN, 0, 2999)  # half-job cap ~1500
            m3 = miner(lanes=1000, span=700)
            b3 = coord._budget(m3, small)
            assert b3 == 1400  # capped to <=1500, re-rounded to 2x700
            # below one span the cap wins outright (exhaustion beats
            # alignment on jobs smaller than two spans)
            tiny = job(PowMode.MIN, 0, 999)
            b4 = coord._budget(m3, tiny)
            assert 0 < b4 <= 500

            # scrypt: divisor-scaled with the RPC-amortization floor...
            sc = job(PowMode.SCRYPT, 0, (1 << 20) - 1)
            b5 = coord._budget(miner(lanes=1), sc)
            assert b5 == SCRYPT_MIN_CHUNK
            # ...which the half-job anti-monopoly cap beats on tiny jobs
            sc_tiny = job(PowMode.SCRYPT, 0, 599)
            b6 = coord._budget(miner(lanes=1), sc_tiny)
            assert b6 == 300  # (599 + 2) // 2, under the 512 floor
        finally:
            await coord.close()

    run(scenario())


def test_cancel_interrupts_pipelined_scrypt_within_one_span():
    """Cancel-latency guard for the depth-2 double-buffered device loops
    (``search.pipeline_spans`` — VERDICT r5 weak #2): pipelining must
    not move the role loop's yield points, so a Cancel still lands
    within ONE resolved span — the speculative in-flight batch is
    abandoned, never waited for. Client A's effectively-unbounded scrypt
    job is cancelled by A's death mid-pipeline; client B's tiny MIN job
    must then complete promptly, which fails if the pipelined generator
    stops yielding between batches or drains its queue before noticing
    the Cancel."""

    async def scenario():
        import time as _time

        from tpuminter.jax_worker import JaxMiner

        # warm the (64,)-shaped scrypt compile OUTSIDE the timed
        # scenario so the cancel window measures batches, not XLA
        warm = JaxMiner(scrypt_batch=64)
        warm_req = Request(job_id=99, mode=PowMode.SCRYPT, lower=0, upper=63,
                           header=chain.GENESIS_HEADER.pack(), target=1)
        for _ in warm.mine(warm_req):
            pass

        cluster = await Cluster.create(
            n_miners=1, chunk_size=1 << 20,
            miner_factory=lambda: JaxMiner(scrypt_batch=64, depth=2),
        )
        try:
            from tpuminter.lsp import LspClient
            from tpuminter.protocol import encode_msg

            doomed = await LspClient.connect(
                "127.0.0.1", cluster.coord.port, FAST
            )
            doomed.write(encode_msg(Request(
                job_id=1, mode=PowMode.SCRYPT, lower=0, upper=(1 << 20) - 1,
                header=chain.GENESIS_HEADER.pack(), target=1,
            )))
            await asyncio.sleep(1.0)  # miner is now pipelining batches
            req_b = Request(job_id=2, mode=PowMode.MIN, lower=0, upper=500,
                            data=b"after pipelined cancel")
            submit_b = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req_b, params=FAST)
            )
            await asyncio.sleep(0.2)
            assert not submit_b.done()  # queued behind A's in-flight chunk
            t0 = _time.monotonic()
            await doomed.close()  # A dies → Cancel lands mid-pipeline
            result = await asyncio.wait_for(submit_b, 30.0)
            print(f"pipelined-cancel: death→B-complete "
                  f"{_time.monotonic() - t0:.2f}s")
            assert (result.hash_value, result.nonce) == brute_min(
                b"after pipelined cancel", 0, 500
            )
        finally:
            await cluster.close()

    run(scenario(), timeout=120)


# ---------------------------------------------------------------------------
# binary-codec interop (ISSUE 4 acceptance): mixed-version peers share a
# wire with no flag day — codec choice is negotiated per connection and
# degrades to JSON whenever either side doesn't speak binary
# ---------------------------------------------------------------------------

def test_binary_coordinator_interops_with_json_only_worker():
    """A binary-codec coordinator (shipping default) serving a worker
    pinned to JSON (the pre-binary peer stand-in): no binary payload
    may reach the worker, and the answer is still brute-force exact."""

    async def scenario():
        cluster = await Cluster.create(n_miners=0, chunk_size=1024)
        task = asyncio.ensure_future(run_miner(
            "127.0.0.1", cluster.coord.port, CpuMiner(), params=FAST,
            binary=False,
        ))
        cluster.miner_tasks.append(task)
        await asyncio.sleep(0.05)
        try:
            req = Request(job_id=4, mode=PowMode.MIN, lower=0, upper=6000,
                          data=b"json-only worker")
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST),
                30.0,
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"json-only worker", 0, 6000
            )
            # the negotiation really resolved to JSON for this conn
            assert all(
                not m.binary for m in cluster.coord._miners.values()
            )
        finally:
            await cluster.close()

    run(scenario())


def test_json_coordinator_interops_with_binary_capable_worker():
    """The other direction: an old (JSON-pinned) coordinator serving a
    modern worker that ADVERTISES binary. The coordinator never sends a
    binary payload, so the worker never flips its own send side — the
    advertisement alone must not break anything."""

    async def scenario():
        cluster = await Cluster.create(
            n_miners=1, chunk_size=1024, binary_codec=False
        )
        try:
            req = Request(job_id=5, mode=PowMode.MIN, lower=0, upper=6000,
                          data=b"json-only coordinator")
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST),
                30.0,
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"json-only coordinator", 0, 6000
            )
            assert all(
                not m.binary for m in cluster.coord._miners.values()
            )
        finally:
            await cluster.close()

    run(scenario())


def test_binary_both_ends_negotiates_and_answers_exactly():
    """Shipping defaults on both ends: the Join advertisement flips the
    coordinator, the coordinator's first binary Assign flips the
    worker, binary traffic actually flows, and the fold is still
    brute-force exact (the codec can never change meaning)."""
    from tpuminter import protocol

    async def scenario():
        before = dict(protocol.codec_stats)
        cluster = await Cluster.create(n_miners=2, chunk_size=1024)
        try:
            req = Request(job_id=6, mode=PowMode.MIN, lower=0, upper=9000,
                          data=b"binary both ends")
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST),
                30.0,
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"binary both ends", 0, 9000
            )
            assert all(m.binary for m in cluster.coord._miners.values())
            # both directions used the fast path: binary messages were
            # encoded AND decoded in this process (assigns out, results
            # back)
            assert protocol.codec_stats["binary_encoded"] > before[
                "binary_encoded"
            ]
            assert protocol.codec_stats["binary_decoded"] > before[
                "binary_decoded"
            ]
        finally:
            await cluster.close()

    run(scenario())


def test_hedge_loser_with_pipelined_chunks_releases_them_all():
    """Pipelining × hedging regression: the hedge-loser Cancel is
    job-scoped, so a loser holding OTHER chunks of the same job
    (depth-2 pipeline) silently abandons them — the coordinator must
    release and requeue every one of them at settlement, or the job
    could only finish via a second hedge cycle (or never). Pinned by
    the hedge count: exactly ONE hedge suffices, with the loser's
    other chunk completing through a normal requeue."""
    import time as _time

    from tpuminter.worker import Miner

    class StallMiner(Miner):
        backend = "stall"
        lanes = 1

        def mine(self, request):
            while True:
                _time.sleep(0.05)
                yield None

    async def scenario():
        cluster = await Cluster.create(
            n_miners=0, chunk_size=3000, hedge_after=0.5
        )
        # join order pins breadth-first dispatch: stall takes chunks A
        # and C (depth 2), cpu takes B
        await cluster.add_miner(StallMiner())
        await cluster.add_miner(CpuMiner(batch=256))
        try:
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=8999,
                          data=b"hedge pipeline leak")
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST),
                30.0,
            )
            assert (result.hash_value, result.nonce) == brute_min(
                b"hedge pipeline leak", 0, 8999
            )
            # one hedge rescued the stalled HEAD chunk; the loser's
            # second pipelined chunk was requeued at settlement — a
            # second hedge (the pre-fix self-heal path) means the
            # release leaked
            assert cluster.coord.stats["chunks_hedged"] == 1, (
                cluster.coord.stats
            )
            assert cluster.coord.stats["chunks_requeued"] >= 1
        finally:
            await cluster.close()

    run(scenario())
