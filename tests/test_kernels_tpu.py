"""Pallas kernel tests — run on the real TPU chip via a subprocess
(tests/conftest.py pins the test process itself to the fake CPU mesh,
and the kernels only compile on a TPU backend; SURVEY.md §4's
interpret-mode plan is unworkable here because XLA:CPU cannot compile
the unrolled SHA graphs in reasonable time).

The subprocess asserts bit-exactness of every kernel against the
host-side chain primitives, then the standard Worker-interface behavior
of TpuMiner. Skipped when no TPU is reachable."""

import os
import subprocess
import sys

import pytest

_SCRIPT = r"""
import struct
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
assert jax.default_backend() != "cpu", f"no TPU: {jax.default_backend()}"
from tpuminter import chain
from tpuminter.ops import sha256 as ops
from tpuminter.kernels import (
    pallas_min_toy, pallas_search_candidates, pallas_search_candidates_hdr,
    pallas_search_target, pallas_sha256_batch,
)
from tpuminter.protocol import PowMode, Request
from tpuminter.tpu_worker import TpuMiner

# --- digest kernel: bit-exact vs hashlib ---------------------------------
tmpl = ops.header_template(chain.GENESIS_HEADER.pack())
n = 2048
rng = np.random.default_rng(0)
nonces = rng.integers(0, 2**32, n, dtype=np.uint32)
got = np.asarray(pallas_sha256_batch(tmpl, jnp.zeros(n, jnp.uint32), jnp.asarray(nonces)))
for i in [0, 1, 777, 2047]:
    want = chain.GENESIS_HEADER.with_nonce(int(nonces[i])).block_hash()
    assert got[i].astype(">u4").tobytes() == want, f"digest {i}"

t2 = ops.toy_template(b"subprocess toy")
hi = jnp.asarray((nonces.astype(np.uint64) >> 3).astype(np.uint32))
got2 = np.asarray(pallas_sha256_batch(t2, hi, jnp.asarray(nonces)))
for i in [0, 99]:
    nn = (int(hi[i]) << 32) | int(nonces[i])
    import hashlib
    want = hashlib.sha256(b"subprocess toy" + struct.pack(">Q", nn)).digest()
    assert got2[i].astype(">u4").tobytes() == want, f"toy digest {i}"
print("DIGEST-OK")

# --- search kernel: genesis find, masking, exact exhausted min -----------
gn = chain.GENESIS_HEADER.nonce
tw = tuple(int(x) for x in ops.target_to_words(chain.bits_to_target(0x1D00FFFF)))
f, first, _, _ = pallas_search_target(tmpl, tw, jnp.uint32(gn - 5000), 5001)
assert int(f) == 1 and gn - 5000 + int(first) == gn
f2, _, _, _ = pallas_search_target(tmpl, tw, jnp.uint32(gn - 5000), 5000)
assert int(f2) == 0  # winner just past the limit is masked
f3, _, mw3, mo3 = pallas_search_target(tmpl, tw, jnp.uint32(0), 3000)
hww = np.asarray(ops.hash_words_be(
    ops.double_sha256_header_batch(tmpl, jnp.arange(3000, dtype=jnp.uint32))))
wi = min(range(3000), key=lambda i: (tuple(hww[i]), i))
assert int(f3) == 0 and int(mo3) == wi and (np.asarray(mw3) == hww[wi]).all()
print("SEARCH-OK")

# --- candidates kernel: find, cap filter, masking ------------------------
cap1 = jnp.uint32(tw[1])  # diff-1 target word 1 = 0xFFFF0000
fc, offc = pallas_search_candidates(tmpl, jnp.uint32(gn - 5000), 1 << 14, 8, cap1)
assert int(fc) == 1 and gn - 5000 + int(offc) == gn
fc2, _ = pallas_search_candidates(tmpl, jnp.uint32(gn - 5000), 5000, 8, cap1)
assert int(fc2) == 0  # winner just past the (ragged, masked) limit
fc3, _ = pallas_search_candidates(tmpl, jnp.uint32(gn - 5000), 1 << 14, 8, jnp.uint32(0))
assert int(fc3) == 0  # cap=0 rejects genesis (its hash word 1 != 0)
print("CAND-OK")

# --- toy kernel: 64-bit base, ragged n, exact min ------------------------
t3 = ops.toy_template(b"kernel min")
base = (1 << 33) + 7
fh, fl, off = pallas_min_toy(t3, jnp.uint32(base >> 32), jnp.uint32(base & 0xFFFFFFFF), 2500)
got = ((int(fh) << 32) | int(fl), base + int(off))
want = min((chain.toy_hash(b"kernel min", base + i), base + i) for i in range(2500))
assert got == want, (got, want)
print("TOY-OK")

# --- TpuMiner through the Miner interface --------------------------------
def drain(gen):
    for item in gen:
        if item is not None:
            return item
    raise AssertionError("no Result")

miner = TpuMiner(slab=1 << 16)
req = Request(job_id=1, mode=PowMode.TARGET, lower=gn - 600, upper=gn + 600,
              header=chain.GENESIS_HEADER.pack(),
              target=chain.bits_to_target(0x1D00FFFF))
r = drain(miner.mine(req))
assert r.found and r.nonce == gn and r.hash_value == chain.GENESIS_HEADER.block_hash_int()
assert r.searched == 601

req2 = Request(job_id=2, mode=PowMode.TARGET, lower=0, upper=999,
               header=chain.GENESIS_HEADER.pack(),
               target=chain.bits_to_target(0x1D00FFFF))
# fast path: candidate-free exhausted chunk reports the sentinel hash
r2f = drain(miner.mine(req2))
assert not r2f.found and r2f.hash_value == (1 << 256) - 1
assert r2f.searched == 1000
# exact-min compat path matches the host-side minimum bit-for-bit
r2 = drain(TpuMiner(slab=1 << 16, exact_min=True).mine(req2))
want2 = min(
    (chain.hash_to_int(chain.GENESIS_HEADER.with_nonce(i).block_hash()), i)
    for i in range(1000)
)
assert not r2.found and (r2.hash_value, r2.nonce) == want2

req3 = Request(job_id=3, mode=PowMode.MIN, lower=50, upper=4049, data=b"tpu min")
r3 = drain(miner.mine(req3))
want3 = min((chain.toy_hash(b"tpu min", i), i) for i in range(50, 4050))
assert (r3.hash_value, r3.nonce) == want3
print("MINER-OK")

# --- dynamic-header kernel ≡ baked kernel (the extranonce-roll consumer) --
mid_dyn = jnp.asarray(tmpl.midstate_array())
tw_dyn = jnp.asarray(np.array(chain.GENESIS_HEADER.tail_words(), np.uint32))
fd, od = pallas_search_candidates_hdr(mid_dyn, tw_dyn, jnp.uint32(gn - 5000), 1 << 14, 8, cap1)
assert int(fd) == 1 and gn - 5000 + int(od) == gn
fd2, _ = pallas_search_candidates_hdr(mid_dyn, tw_dyn, jnp.uint32(gn - 5000), 5000, 8, cap1)
assert int(fd2) == 0  # ragged-limit masking
print("DYN-OK")

# --- >2^32 rolled search: exhaust extranonce 0's full 32-bit space on
# device, roll the merkle root ON DEVICE, win at extranonce 1
# (BASELINE.json:9-10; eval configs 3-4). Fixture pre-enumerated on this
# chip: with seed-0 coinbase/branch, en=0's only top-word-zero candidate
# hashes above TGT while en=1's second candidate (nonce 2804947108)
# hashes exactly TGT — hardcoded, then re-proven below against hashlib.
rng2 = np.random.RandomState(0)
cb_prefix = rng2.bytes(41); cb_suffix = rng2.bytes(60)
cb_branch = tuple(rng2.bytes(32) for _ in range(2))
TGT = 0x6d278107d5385a15ebb7b627ad622562f7bc65132eba75b00c300cde
G_WIN = (1 << 32) + 2804947108
req4 = Request(job_id=4, mode=PowMode.TARGET, lower=0, upper=(2 << 32) - 1,
               header=chain.GENESIS_HEADER.pack(), target=TGT,
               coinbase_prefix=cb_prefix, coinbase_suffix=cb_suffix,
               extranonce_size=4, branch=cb_branch, nonce_bits=32)
r4 = drain(TpuMiner().mine(req4))
assert r4.found and r4.nonce == G_WIN, (r4.nonce, G_WIN)
en4, n4 = chain.split_global(r4.nonce, 32)
assert en4 == 1  # the 32-bit space was exhausted and rolled past
cb = chain.CoinbaseTemplate(cb_prefix, cb_suffix, 4)
p76 = chain.rolled_header(chain.GENESIS_HEADER.pack(), cb, cb_branch, en4).pack()[:76]
want4 = chain.hash_to_int(chain.dsha256(p76 + struct.pack("<I", n4)))
assert r4.hash_value == want4 == TGT  # bit-for-bit vs hashlib
assert r4.searched == G_WIN + 1      # exact coverage accounting
print("ROLL-OK")

# --- rolled tracking path (toy-easy target, shrunken nonce space):
# same fixture as tests/test_extranonce.py (winner at extranonce 2)
H_MIN = 0x24bee56364831b90d0d828f4e96df79a0a49046d315a7f3c2d8284c5cfac26
req5 = Request(job_id=5, mode=PowMode.TARGET, lower=0, upper=(4 << 10) - 1,
               header=chain.GENESIS_HEADER.pack(), target=H_MIN,
               coinbase_prefix=cb_prefix, coinbase_suffix=cb_suffix,
               extranonce_size=4, branch=cb_branch, nonce_bits=10)
r5 = drain(TpuMiner(slab=1 << 16).mine(req5))
assert r5.found and r5.nonce == 2698 and r5.hash_value == H_MIN
print("ROLL-TRACK-OK")

# --- pod paths on the real chip (1-chip mesh): the shard_map'd Pallas
# MIN sweep (full span + ragged single-chip tail) and the exact-min
# TARGET sweep, both bit-exact vs host brute force
from tpuminter.parallel import make_mesh
from tpuminter.pod_worker import PodMiner
pm = PodMiner(mesh=make_mesh(jax.devices()[:1]), slab_per_device=1 << 12,
              n_slabs=2, kernel="pallas")
req6 = Request(job_id=6, mode=PowMode.MIN, lower=10, upper=(1 << 12) + 500,
               data=b"pod min tpu")
r6 = drain(pm.mine(req6))
want6 = min((chain.toy_hash(b"pod min tpu", i), i)
            for i in range(10, (1 << 12) + 501))
assert (r6.hash_value, r6.nonce) == want6
print("POD-MIN-OK")

pe = PodMiner(mesh=make_mesh(jax.devices()[:1]), slab_per_device=256,
              n_slabs=2, kernel="pallas", exact_min=True)
req7 = Request(job_id=7, mode=PowMode.TARGET, lower=0, upper=999,
               header=chain.GENESIS_HEADER.pack(),
               target=chain.bits_to_target(0x1D00FFFF))
r7 = drain(pe.mine(req7))
assert not r7.found and (r7.hash_value, r7.nonce) == want2
print("POD-EXACT-OK")
print("ALL-TPU-KERNEL-TESTS-PASSED")
"""


def _tpu_env():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    return env


def test_kernels_on_real_tpu():
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        env=_tpu_env(),
        capture_output=True,
        text=True,
        timeout=570,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if "no TPU:" in (proc.stdout + proc.stderr):
        # LOUD skip (VERDICT r2 weak #5): a green suite does NOT imply
        # the compiled kernels were verified. Set TPUMINTER_REQUIRE_TPU=1
        # to turn an unreachable chip into a hard failure.
        if os.environ.get("TPUMINTER_REQUIRE_TPU") == "1":
            pytest.fail(
                "TPU required (TPUMINTER_REQUIRE_TPU=1) but no TPU "
                f"backend reachable:\n{proc.stdout}\n{proc.stderr[-1000:]}"
            )
        pytest.skip(
            "NO TPU REACHABLE — the compiled Pallas kernels were NOT "
            "verified by this run; re-run standalone on a chip or set "
            "TPUMINTER_REQUIRE_TPU=1 to make this a failure"
        )
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-3000:]}"
    )
    assert "ALL-TPU-KERNEL-TESTS-PASSED" in proc.stdout
