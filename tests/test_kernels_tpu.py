"""Pallas / device-pipeline tests on the real TPU chip, one subprocess
per section (tests/conftest.py pins the test process itself to the fake
CPU mesh, and the kernels only compile on a TPU backend; SURVEY.md §4's
interpret-mode plan is unworkable here because XLA:CPU cannot compile
the unrolled SHA graphs in reasonable time).

Each section is an independently-failing pytest ID (VERDICT r4 weak #5:
the former monolithic 4-minute blob localized nothing), sharing one
persistent compilation cache so reruns stay warm.  Sections assert
bit-exactness against the host-side chain primitives (hashlib), then
the Worker-interface behavior of TpuMiner/PodMiner — including the pod
SCRYPT sweep and pod exact-min programs on the 1-chip mesh (VERDICT r4
missing #1: no device program may exist that has never executed on
silicon).  Skipped (loudly) when no TPU is reachable."""

import os
import subprocess
import sys

import pytest

_PRELUDE = r"""
import struct
import hashlib
import numpy as np, jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/tpuminter-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
assert jax.default_backend() != "cpu", f"no TPU: {jax.default_backend()}"
from tpuminter import chain
from tpuminter.ops import sha256 as ops
from tpuminter.protocol import PowMode, Request

GEN = chain.GENESIS_HEADER
gn = GEN.nonce
tmpl = ops.header_template(GEN.pack())
tw = tuple(int(x) for x in ops.target_to_words(chain.bits_to_target(0x1D00FFFF)))
cap1 = jnp.uint32(tw[1])  # diff-1 target word 1 = 0xFFFF0000

def drain(gen):
    for item in gen:
        if item is not None:
            return item
    raise AssertionError("no Result")
"""

_SECTIONS = {
    # --- digest kernel: bit-exact vs hashlib ------------------------------
    "digest": r"""
from tpuminter.kernels import pallas_sha256_batch
n = 2048
rng = np.random.default_rng(0)
nonces = rng.integers(0, 2**32, n, dtype=np.uint32)
got = np.asarray(pallas_sha256_batch(tmpl, jnp.zeros(n, jnp.uint32), jnp.asarray(nonces)))
for i in [0, 1, 777, 2047]:
    want = GEN.with_nonce(int(nonces[i])).block_hash()
    assert got[i].astype(">u4").tobytes() == want, f"digest {i}"

t2 = ops.toy_template(b"subprocess toy")
hi = jnp.asarray((nonces.astype(np.uint64) >> 3).astype(np.uint32))
got2 = np.asarray(pallas_sha256_batch(t2, hi, jnp.asarray(nonces)))
for i in [0, 99]:
    nn = (int(hi[i]) << 32) | int(nonces[i])
    want = hashlib.sha256(b"subprocess toy" + struct.pack(">Q", nn)).digest()
    assert got2[i].astype(">u4").tobytes() == want, f"toy digest {i}"
print("SECTION-OK")
""",
    # --- search kernel: genesis find, masking, exact exhausted min --------
    "search": r"""
from tpuminter.kernels import pallas_search_target
f, first, _, _ = pallas_search_target(tmpl, tw, jnp.uint32(gn - 5000), 5001)
assert int(f) == 1 and gn - 5000 + int(first) == gn
f2, _, _, _ = pallas_search_target(tmpl, tw, jnp.uint32(gn - 5000), 5000)
assert int(f2) == 0  # winner just past the limit is masked
f3, _, mw3, mo3 = pallas_search_target(tmpl, tw, jnp.uint32(0), 3000)
hww = np.asarray(ops.hash_words_be(
    ops.double_sha256_header_batch(tmpl, jnp.arange(3000, dtype=jnp.uint32))))
wi = min(range(3000), key=lambda i: (tuple(hww[i]), i))
assert int(f3) == 0 and int(mo3) == wi and (np.asarray(mw3) == hww[wi]).all()
print("SECTION-OK")
""",
    # --- candidates kernel: find, cap filter, masking ---------------------
    "candidates": r"""
from tpuminter.kernels import pallas_search_candidates
fc, offc = pallas_search_candidates(tmpl, jnp.uint32(gn - 5000), 1 << 14, 8, cap1)
assert int(fc) == 1 and gn - 5000 + int(offc) == gn
fc2, _ = pallas_search_candidates(tmpl, jnp.uint32(gn - 5000), 5000, 8, cap1)
assert int(fc2) == 0  # winner just past the (ragged, masked) limit
fc3, _ = pallas_search_candidates(tmpl, jnp.uint32(gn - 5000), 1 << 14, 8, jnp.uint32(0))
assert int(fc3) == 0  # cap=0 rejects genesis (its hash word 1 != 0)
print("SECTION-OK")
""",
    # --- toy kernel: 64-bit base, ragged n, exact min ---------------------
    "toy_min": r"""
from tpuminter.kernels import pallas_min_toy
t3 = ops.toy_template(b"kernel min")
base = (1 << 33) + 7
fh, fl, off = pallas_min_toy(t3, jnp.uint32(base >> 32), jnp.uint32(base & 0xFFFFFFFF), 2500)
got = ((int(fh) << 32) | int(fl), base + int(off))
want = min((chain.toy_hash(b"kernel min", base + i), base + i) for i in range(2500))
assert got == want, (got, want)
print("SECTION-OK")
""",
    # --- TpuMiner through the Miner interface -----------------------------
    "miner": r"""
from tpuminter.tpu_worker import TpuMiner
miner = TpuMiner(slab=1 << 16)
req = Request(job_id=1, mode=PowMode.TARGET, lower=gn - 600, upper=gn + 600,
              header=GEN.pack(), target=chain.bits_to_target(0x1D00FFFF))
r = drain(miner.mine(req))
assert r.found and r.nonce == gn and r.hash_value == GEN.block_hash_int()
assert r.searched == 601

req2 = Request(job_id=2, mode=PowMode.TARGET, lower=0, upper=999,
               header=GEN.pack(), target=chain.bits_to_target(0x1D00FFFF))
# fast path: candidate-free exhausted chunk reports the sentinel hash
r2f = drain(miner.mine(req2))
assert not r2f.found and r2f.hash_value == (1 << 256) - 1
assert r2f.searched == 1000
# exact-min compat path matches the host-side minimum bit-for-bit
r2 = drain(TpuMiner(slab=1 << 16, exact_min=True).mine(req2))
want2 = min(
    (chain.hash_to_int(GEN.with_nonce(i).block_hash()), i) for i in range(1000)
)
assert not r2.found and (r2.hash_value, r2.nonce) == want2

req3 = Request(job_id=3, mode=PowMode.MIN, lower=50, upper=4049, data=b"tpu min")
r3 = drain(miner.mine(req3))
want3 = min((chain.toy_hash(b"tpu min", i), i) for i in range(50, 4050))
assert (r3.hash_value, r3.nonce) == want3
# the MIN contract (VERDICT r5 next #7), on the pipelined loop: always
# found=True with full searched accounting
assert r3.found is True and r3.searched == 4000
print("SECTION-OK")
""",
    # --- dynamic-header kernel ≡ baked kernel (extranonce-roll consumer) --
    "dyn_header": r"""
from tpuminter.kernels import pallas_search_candidates_hdr
mid_dyn = jnp.asarray(tmpl.midstate_array())
tw_dyn = jnp.asarray(np.array(GEN.tail_words(), np.uint32))
fd, od = pallas_search_candidates_hdr(mid_dyn, tw_dyn, jnp.uint32(gn - 5000), 1 << 14, 8, cap1)
assert int(fd) == 1 and gn - 5000 + int(od) == gn
fd2, _ = pallas_search_candidates_hdr(mid_dyn, tw_dyn, jnp.uint32(gn - 5000), 5000, 8, cap1)
assert int(fd2) == 0  # ragged-limit masking
print("SECTION-OK")
""",
    # --- >2^32 rolled search: exhaust extranonce 0's full 32-bit space on
    # device, roll the merkle root ON DEVICE, win at extranonce 1
    # (BASELINE.json:9-10; eval configs 3-4). Fixture pre-enumerated on
    # this chip: with seed-0 coinbase/branch, en=0's only top-word-zero
    # candidate hashes above TGT while en=1's second candidate (nonce
    # 2804947108) hashes exactly TGT — hardcoded, re-proven vs hashlib.
    "rolled": r"""
from tpuminter.tpu_worker import TpuMiner
rng2 = np.random.RandomState(0)
cb_prefix = rng2.bytes(41); cb_suffix = rng2.bytes(60)
cb_branch = tuple(rng2.bytes(32) for _ in range(2))
TGT = 0x6d278107d5385a15ebb7b627ad622562f7bc65132eba75b00c300cde
G_WIN = (1 << 32) + 2804947108
req4 = Request(job_id=4, mode=PowMode.TARGET, lower=0, upper=(2 << 32) - 1,
               header=GEN.pack(), target=TGT,
               coinbase_prefix=cb_prefix, coinbase_suffix=cb_suffix,
               extranonce_size=4, branch=cb_branch, nonce_bits=32)
r4 = drain(TpuMiner().mine(req4))
assert r4.found and r4.nonce == G_WIN, (r4.nonce, G_WIN)
en4, n4 = chain.split_global(r4.nonce, 32)
assert en4 == 1  # the 32-bit space was exhausted and rolled past
cb = chain.CoinbaseTemplate(cb_prefix, cb_suffix, 4)
p76 = chain.rolled_header(GEN.pack(), cb, cb_branch, en4).pack()[:76]
want4 = chain.hash_to_int(chain.dsha256(p76 + struct.pack("<I", n4)))
assert r4.hash_value == want4 == TGT  # bit-for-bit vs hashlib
assert r4.searched == G_WIN + 1      # exact coverage accounting

# rolled tracking path (toy-easy target, shrunken nonce space): same
# fixture as tests/test_extranonce.py (winner at extranonce 2)
H_MIN = 0x24bee56364831b90d0d828f4e96df79a0a49046d315a7f3c2d8284c5cfac26
req5 = Request(job_id=5, mode=PowMode.TARGET, lower=0, upper=(4 << 10) - 1,
               header=GEN.pack(), target=H_MIN,
               coinbase_prefix=cb_prefix, coinbase_suffix=cb_suffix,
               extranonce_size=4, branch=cb_branch, nonce_bits=10)
r5 = drain(TpuMiner(slab=1 << 16).mine(req5))
assert r5.found and r5.nonce == 2698 and r5.hash_value == H_MIN
print("SECTION-OK")
""",
    # --- batched rolled sweep (ISSUE 7): the per-row-midstate kernel's
    # rows ≡ singleton dynamic-header calls (found flag, first offset,
    # dynamic valid masking), and TpuMiner's batched fast path ≡ the
    # roll_batch=1 per-segment baseline on the same fixtures
    "rolled_batched": r"""
from tpuminter.kernels import (
    pallas_search_candidates_hdr, pallas_search_candidates_hdr_batch,
)
from tpuminter.ops import merkle
from tpuminter.tpu_worker import TpuMiner
rng2 = np.random.RandomState(0)
cb_prefix = rng2.bytes(41); cb_suffix = rng2.bytes(60)
cb_branch = tuple(rng2.bytes(32) for _ in range(2))
roll_b = merkle.make_extranonce_roll_batch(
    GEN.pack(), cb_prefix, cb_suffix, 4, cb_branch)
mids, tails = roll_b(jnp.zeros(3, jnp.uint32),
                     jnp.asarray(np.array([0, 1, 2], np.uint32)))
W = 1 << 14
bases = np.array([100, 2804947108 - 5000, 100], np.uint32)  # row 1 wins
valids = np.array([W, W, 3000], np.uint32)
fb, ob = pallas_search_candidates_hdr_batch(
    mids, tails, jnp.asarray(bases), jnp.asarray(valids), W, 8, cap1)
fb, ob = np.asarray(fb), np.asarray(ob)
for i in range(3):
    f1, o1 = pallas_search_candidates_hdr(
        mids[i], tails[i], jnp.uint32(int(bases[i])),
        int(valids[i]), 8, cap1)
    assert (int(fb[i]) != 0) == (int(f1) != 0), i
    if int(fb[i]):
        assert int(ob[i]) == int(o1), i
assert int(fb[1]) == 1 and int(bases[1]) + int(ob[1]) == 2804947108
assert int(fb[2]) == 0  # dynamic valid masking trims row 2's sweep

# TpuMiner batched == per-segment baseline, fast + tracking fixtures
TGT = 0x6d278107d5385a15ebb7b627ad622562f7bc65132eba75b00c300cde
req7 = Request(job_id=7, mode=PowMode.TARGET, lower=0, upper=(2 << 32) - 1,
               header=GEN.pack(), target=TGT,
               coinbase_prefix=cb_prefix, coinbase_suffix=cb_suffix,
               extranonce_size=4, branch=cb_branch, nonce_bits=32)
rb = drain(TpuMiner(roll_batch=4).mine(req7))
r1 = drain(TpuMiner(roll_batch=1).mine(req7))
assert (rb.found, rb.nonce, rb.hash_value) == (r1.found, r1.nonce, r1.hash_value)
assert rb.nonce == (1 << 32) + 2804947108
print("SECTION-OK")
""",
    # --- shared-compression scheduling (ISSUE 16): the sched=True kernel
    # body (per-row schedule prefix hoisted via sym.prepare_hdr) returns
    # bit-identical (found, first_off) rows to the sched=False baseline
    # on the real chip — winner rows, ragged valids, and padding rows —
    # and TpuMiner's production default (sched_share on) still lands the
    # exact cross-extranonce winner of the rolled_batched fixture
    "sched_share": r"""
from tpuminter.kernels import pallas_search_candidates_hdr_batch
from tpuminter.ops import merkle
from tpuminter.tpu_worker import TpuMiner
rng3 = np.random.RandomState(0)
cb_prefix = rng3.bytes(41); cb_suffix = rng3.bytes(60)
cb_branch = tuple(rng3.bytes(32) for _ in range(2))
roll_b = merkle.make_extranonce_roll_batch(
    GEN.pack(), cb_prefix, cb_suffix, 4, cb_branch)
mids, tails = roll_b(jnp.zeros(3, jnp.uint32),
                     jnp.asarray(np.array([0, 1, 2], np.uint32)))
W = 1 << 14
bases = np.array([100, 2804947108 - 5000, 100], np.uint32)  # row 1 wins
valids = np.array([W, W, 0], np.uint32)  # row 2: pure padding
args = (mids, tails, jnp.asarray(bases), jnp.asarray(valids), W, 8, cap1)
f0, o0 = (np.asarray(x) for x in
          pallas_search_candidates_hdr_batch(*args, sched=False))
f1, o1 = (np.asarray(x) for x in
          pallas_search_candidates_hdr_batch(*args, sched=True))
assert np.array_equal(f0, f1) and int(f1[1]) == 1
assert int(o0[1]) == int(o1[1]) == 2804947108 - int(bases[1])

# end-to-end: production default (sched_share on) == off, and both land
# the known cross-extranonce winner through the whole candidate plane
TGT = 0x6d278107d5385a15ebb7b627ad622562f7bc65132eba75b00c300cde
req8 = Request(job_id=8, mode=PowMode.TARGET, lower=0, upper=(2 << 32) - 1,
               header=GEN.pack(), target=TGT,
               coinbase_prefix=cb_prefix, coinbase_suffix=cb_suffix,
               extranonce_size=4, branch=cb_branch, nonce_bits=32)
r_on = drain(TpuMiner(roll_batch=4).mine(req8))
r_off = drain(TpuMiner(roll_batch=4, sched_share=False).mine(req8))
assert (r_on.found, r_on.nonce, r_on.hash_value) == (
    r_off.found, r_off.nonce, r_off.hash_value)
assert r_on.nonce == (1 << 32) + 2804947108
print("SECTION-OK")
""",
    # --- pod paths on the real chip (1-chip mesh): the shard_map'd Pallas
    # MIN sweep (full span + ragged tail) and the exact-min TARGET sweep
    # (build_exact_sweep_pallas: pallas_search_target per chip, pipelined
    # host loop) — exhausted-min bit-exact vs hashlib AND the winner path
    "pod": r"""
from tpuminter.parallel import make_mesh
from tpuminter.pod_worker import PodMiner
pm = PodMiner(mesh=make_mesh(jax.devices()[:1]), slab_per_device=1 << 12,
              n_slabs=2, kernel="pallas")
req6 = Request(job_id=6, mode=PowMode.MIN, lower=10, upper=(1 << 12) + 500,
               data=b"pod min tpu")
r6 = drain(pm.mine(req6))
want6 = min((chain.toy_hash(b"pod min tpu", i), i)
            for i in range(10, (1 << 12) + 501))
assert (r6.hash_value, r6.nonce) == want6
assert r6.found is True and r6.searched == (1 << 12) + 491  # MIN contract

pe = PodMiner(mesh=make_mesh(jax.devices()[:1]), slab_per_device=256,
              n_slabs=2, kernel="pallas", exact_min=True)
assert pe.exact_min_span == 256  # pallas engine: one slab per chip
req7 = Request(job_id=7, mode=PowMode.TARGET, lower=0, upper=999,
               header=GEN.pack(), target=chain.bits_to_target(0x1D00FFFF))
r7 = drain(pe.mine(req7))
want2 = min(
    (chain.hash_to_int(GEN.with_nonce(i).block_hash()), i) for i in range(1000)
)
assert not r7.found and (r7.hash_value, r7.nonce) == want2
assert r7.searched == 1000

# winner path through the sharded tracking sweep's pod fold: a
# 2-full-span window (no tail) with the genesis winner mid-span-0, so
# the pipelined loop must report it from the POD sweep, in span order
req8 = Request(job_id=8, mode=PowMode.TARGET, lower=gn - 200, upper=gn + 311,
               header=GEN.pack(), target=chain.bits_to_target(0x1D00FFFF))
r8 = drain(pe.mine(req8))
assert r8.found and r8.nonce == gn
assert r8.hash_value == GEN.block_hash_int()
# and the tail winner path: winner inside the ragged single-chip tail
req9 = Request(job_id=9, mode=PowMode.TARGET, lower=gn - 300, upper=gn + 30,
               header=GEN.pack(), target=chain.bits_to_target(0x1D00FFFF))
r9 = drain(pe.mine(req9))
assert r9.found and r9.nonce == gn and r9.hash_value == GEN.block_hash_int()
print("SECTION-OK")
""",
    # --- single-chip scrypt pipeline on silicon: device batch bit-exact
    # vs OpenSSL, then JaxMiner's SCRYPT dialect end to end (the CPU mesh
    # already pins these at small sizes; this proves the REAL backend's
    # compilation — unroll=2 scans, u32 ALU, flat-V gather — agrees)
    "scrypt_chip": r"""
from tpuminter.jax_worker import JaxMiner
from tpuminter.ops import scrypt as scrypt_ops
hdr76 = GEN.pack()[:76]
hw = jnp.asarray(scrypt_ops.header_to_words(hdr76))
nonces = np.array([0, 1, 2, 77777, 0xFFFFFFFF, gn, 12345, 999999], np.uint32)
got = np.asarray(scrypt_ops.scrypt_header_batch(hw, jnp.asarray(nonces)))
for i, n in enumerate(nonces):
    want = hashlib.scrypt(hdr76 + struct.pack("<I", int(n)),
                          salt=hdr76 + struct.pack("<I", int(n)),
                          n=1024, r=1, p=1, maxmem=1 << 26, dklen=32)
    assert got[i].astype(">u4").tobytes() == want, f"scrypt {i}"

upper = 150
all_h = [
    (chain.hash_to_int(chain.scrypt_hash(hdr76 + struct.pack("<I", n))), n)
    for n in range(upper + 1)
]
h_min, n_min = min(all_h)
jm = JaxMiner(scrypt_batch=64)
req = Request(job_id=8, mode=PowMode.SCRYPT, lower=0, upper=upper,
              header=GEN.pack(), target=h_min)
r = drain(jm.mine(req))
assert r.found and (r.nonce, r.hash_value) == (n_min, h_min)
print("SECTION-OK")
""",
    # --- device-lane hashcore engine on silicon (ISSUE 17): the Pallas
    # splitmix kernel compiled by Mosaic (CPU CI only ever interprets
    # it), the pallas-engine sweep programs bit-exact vs the scalar
    # objective at compiled shapes, and the full compute seam under the
    # dev_lanes knob — plus a fresh on-HBM width autotune probe
    "hashcore_dev": r"""
from tpuminter.kernels.splitmix import pallas_splitmix_batch
from tpuminter.ops import splitmix as sm
from tpuminter.workloads import hashcore as hc
from tpuminter.workloads import folds

rng = np.random.default_rng(17)
idx = rng.integers(0, 1 << 64, 4096, dtype=np.uint64)
ih = (idx >> np.uint64(32)).astype(np.uint32)
il = (idx & np.uint64(0xFFFFFFFF)).astype(np.uint32)
seed = 0xFEED_FACE_CAFE_F00D
vh, vl = pallas_splitmix_batch(
    np.uint32(seed >> 32), np.uint32(seed & 0xFFFFFFFF),
    jnp.asarray(ih), jnp.asarray(il))
vh, vl = np.asarray(vh), np.asarray(vl)
for i in [0, 1, 777, 4095]:
    want = hc.objective(seed, int(idx[i]))
    assert (int(vh[i]) << 32) | int(vl[i]) == want, f"splitmix {i}"

# pallas-engine sweep ≡ host folds at a compiled (non-interpret) shape
lo, hi = (1 << 40) + 3, (1 << 40) + 3 + 50_000
vals = [hc.objective(seed, g) for g in range(lo, hi + 1)]
for variant, fold, thr, k in [
    ("fmin", folds.FMin(), 0, 1),
    ("topk", folds.TopK(5), 0, 5),
    ("fmatch", folds.FirstMatch(sorted(vals)[3]), sorted(vals)[3], 1),
    ("fsum", folds.FSum(), 0, 1),
]:
    sweep = sm.LaneSweep(variant, 2048, 8, k, "pallas")
    acc = fold.initial()
    g = lo
    while g <= hi:
        e = min(g + sweep.window - 1, hi)
        acc = fold.combine(
            acc, sweep.resolve(sweep.dispatch(seed, g, e, thr), g, e))
        if fold.is_final(acc):
            break
        g = e + 1
    host = fold.of_batch(lo, vals)
    assert acc == host, (variant, acc, host)

# the compute seam end to end on the default (auto) knob: a tpu-backend
# worker routes through device lanes and matches the host answer
def drive_gen(gen):
    while True:
        try:
            next(gen)
        except StopIteration as stop:
            return stop.value

core = hc.HashCore()
req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=200_000,
              data=hc.pack_params("fmin", seed=seed), workload="hashcore",
              chunk_id=0)
fold = core.fold_for(req)
hc.set_dev_lanes("off")
want = drive_gen(core.compute(req, fold, engine="tpu"))
hc.set_dev_lanes("auto")
before = sm.counters["dispatches"]
got = drive_gen(core.compute(req, fold, engine="tpu"))
assert sm.counters["dispatches"] > before  # device lanes demonstrably ran
assert got == want

# on-HBM width autotune: a real probe on this chip's memory system
sm._autotune_cache.clear()
w = sm.autotune_lane_width("pallas", rows=8)
assert w in (2048, 4096, 8192, 16384)
print("AUTOTUNE-WIDTH", w)
print("SECTION-OK")
""",
    # --- pod SCRYPT sweep on silicon (VERDICT r4 missing #1): the
    # shard_map'd scrypt pipeline + winner/min ICI folds on the 1-chip
    # mesh — winner, exhausted-minimum, and the ragged single-chip tail,
    # all bit-exact vs OpenSSL
    "pod_scrypt": r"""
from tpuminter.parallel import make_mesh
from tpuminter.pod_worker import PodMiner
hdr76 = GEN.pack()[:76]
upper = 64 + 37  # one pod span (1 chip x 64) + ragged tail
all_h = [
    (chain.hash_to_int(chain.scrypt_hash(hdr76 + struct.pack("<I", n))), n)
    for n in range(upper + 1)
]
h_min, n_min = min(all_h)
pm = PodMiner(mesh=make_mesh(jax.devices()[:1]), scrypt_batch=64)

req = Request(job_id=9, mode=PowMode.SCRYPT, lower=0, upper=upper,
              header=GEN.pack(), target=h_min)
r = drain(pm.mine(req))
assert r.found and (r.nonce, r.hash_value) == (n_min, h_min)

req2 = Request(job_id=10, mode=PowMode.SCRYPT, lower=0, upper=upper,
               header=GEN.pack(), target=1)
r2 = drain(pm.mine(req2))
assert not r2.found
assert (r2.hash_value, r2.nonce) == (h_min, n_min)
assert r2.searched == upper + 1
print("SECTION-OK")
""",
}


def _tpu_env():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    return env


_TPU_AVAILABLE = None  # cached module-wide: one probe, not one per section
_TPU_PROBE_OUTPUT = ""  # the probe's stdout+stderr, kept for diagnostics


def _skip_unless_tpu():
    """One cheap cached backend probe for all 10 sections — without it
    the no-TPU skip path boots a full JAX subprocess per section (tens
    of seconds each on this 1-core host) just to rediscover the same
    answer."""
    global _TPU_AVAILABLE, _TPU_PROBE_OUTPUT
    if _TPU_AVAILABLE is None:
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print('BACKEND=' + jax.default_backend())"],
                env=_tpu_env(), capture_output=True, text=True, timeout=180,
            )
        except subprocess.TimeoutExpired as exc:
            # a wedged tunnel can stall libtpu init for many minutes; a
            # probe that cannot answer in 180 s IS a no-TPU answer, and
            # it must be CACHED — an uncaught TimeoutExpired here left
            # _TPU_AVAILABLE unset, so all 10 sections re-probed at
            # 180 s each and blew the tier-1 suite budget (observed)
            _TPU_PROBE_OUTPUT = f"backend probe timed out: {exc}"
            _TPU_AVAILABLE = False
        else:
            _TPU_PROBE_OUTPUT = f"{proc.stdout}\n{proc.stderr[-1500:]}"
            _TPU_AVAILABLE = (
                proc.returncode == 0 and "BACKEND=" in proc.stdout
                and "BACKEND=cpu" not in proc.stdout
            )
    if not _TPU_AVAILABLE:
        # LOUD skip (VERDICT r2 weak #5): a green suite does NOT imply
        # the compiled kernels were verified. Set TPUMINTER_REQUIRE_TPU=1
        # to turn an unreachable chip into a hard failure.
        if os.environ.get("TPUMINTER_REQUIRE_TPU") == "1":
            pytest.fail(
                "TPU required (TPUMINTER_REQUIRE_TPU=1) but no TPU "
                f"backend reachable; probe said:\n{_TPU_PROBE_OUTPUT}"
            )
        pytest.skip(
            "NO TPU REACHABLE — the compiled Pallas kernels were NOT "
            "verified by this run; re-run standalone on a chip or set "
            "TPUMINTER_REQUIRE_TPU=1 to make this a failure"
        )


@pytest.mark.parametrize("section", sorted(_SECTIONS))
def test_kernel_section_on_real_tpu(section):
    _skip_unless_tpu()
    proc = subprocess.run(
        [sys.executable, "-c", _PRELUDE + _SECTIONS[section]],
        env=_tpu_env(),
        capture_output=True,
        text=True,
        timeout=570,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, (
        f"[{section}] stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-3000:]}"
    )
    assert "SECTION-OK" in proc.stdout
