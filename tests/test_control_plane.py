"""Control-plane fast-path tests (the tentpole's harness + semantics).

- the ``scripts/loadgen.py --smoke`` liveness gate in tier-1: a real
  coordinator sustains a fleet-64 result burst with zero loss events
  and no event-loop stall reaching one epoch (the bound past which
  heartbeat/epoch deadlines start missing);
- verification offload ordering: a burst of concurrent scrypt chunk
  Results — verified OFF the event loop in the executor — never drops
  or reorders a winner, and an exhausted job waits for every pending
  verification before reporting its fold;
- the client CLI ``--timeout`` flag (the reference blocks forever).
"""

import asyncio
import os
import struct
import sys
import threading

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter import chain  # noqa: E402
from tpuminter.client import main as client_main  # noqa: E402
from tpuminter.client import submit  # noqa: E402
from tpuminter.coordinator import Coordinator  # noqa: E402
from tpuminter.lsp import LspClient, LspConnectionLost  # noqa: E402
from tpuminter.protocol import (  # noqa: E402
    Assign,
    Join,
    PowMode,
    Request,
    Result,
    Setup,
    decode_msg,
    encode_msg,
)

from tests.test_e2e import FAST, Cluster, brute_min, run  # noqa: E402


def test_loadgen_smoke_fleet64_sustains_without_stalls(capsys):
    """The CLI smoke gate itself (wired into tier-1 per the issue): a
    fleet-64 burst through ``loadgen.main`` — with the Round 9 shipping
    defaults, pipelining depth ≥ 2 and the binary codec ON — must exit
    0: real progress, zero connections declared lost on a healthy
    loopback fleet, and max event-loop stall under one FAST epoch."""
    rc = loadgen.main(["--smoke", "--duration", "1.5", "--json"])
    out = capsys.readouterr().out
    assert rc == 0, f"smoke gate failed: {out}"
    import json as _json

    metrics = _json.loads(out.splitlines()[0])
    assert metrics["fleet"] == 64
    assert metrics["results_per_s"] > 100
    assert metrics["miners_lost"] == 0
    # heartbeat/epoch deadline bound, directly (smoke_check enforces
    # the same thing behind rc; asserted here so a loosened smoke_check
    # cannot silently drop the criterion)
    assert metrics["max_stall_ms"] < 250
    # Round 9 gate (issue satellite): the features under test really
    # were ON — dispatches topped up non-empty pipelines and binary
    # messages actually flowed (smoke_check enforces both behind rc;
    # re-asserted directly for the same reason as the stall bound)
    assert metrics["codec"] == "binary"
    assert metrics["pipeline_depth_configured"] >= 2
    assert metrics["dispatches_pipelined"] > 0
    assert metrics["pipeline_depth_max"] >= 2
    assert metrics["msgs_binary"] > 0
    assert metrics["wire_bytes_per_result"] > 0


def test_loadgen_ab_knobs_reproduce_the_baseline_stack():
    """The A/B seam PERF.md §Round 9 measures through: ``--codec json
    --pipeline 1`` must reproduce the PR 3 stack in the same build —
    no binary message anywhere, no pipelined dispatch, and idle gaps
    that each cost a full assign→result round trip."""
    metrics = asyncio.run(loadgen.run_load(
        4, 2, 1.0, binary=False, pipeline_depth=1
    ))
    assert metrics["codec"] == "json"
    assert metrics["msgs_binary"] == 0
    assert metrics["dispatches_pipelined"] == 0
    assert metrics["pipeline_depth_max"] <= 1
    assert metrics["results_per_s"] > 0
    assert loadgen.smoke_check(metrics) == []  # gate skips when off


def _scrypt_table(hdr: bytes, upper: int) -> dict:
    """nonce → hash_value ground truth for [0, upper] (host scrypt)."""
    prefix = hdr[:76]
    return {
        n: chain.hash_to_int(chain.scrypt_hash(prefix + struct.pack("<I", n)))
        for n in range(upper + 1)
    }


async def _instant_scrypt_actor(port: int, table: dict) -> None:
    """Joins and answers every Assign INSTANTLY from the precomputed
    table (first-winner early exit semantics like CpuMiner), so many
    chunk Results land at the coordinator in one burst and their
    (executor-offloaded) verifications overlap."""
    w = await LspClient.connect("127.0.0.1", port, FAST)
    w.write(encode_msg(Join(backend="instant-scrypt", lanes=1)))
    templates = {}
    try:
        while True:
            msg = decode_msg(await w.read())
            if isinstance(msg, Setup):
                templates[msg.request.job_id] = msg.request
            elif isinstance(msg, Assign):
                req = templates.get(msg.job_id)
                if req is None:
                    continue
                best = None
                found = False
                searched = 0
                for n in range(msg.lower, msg.upper + 1):
                    h = table[n]
                    searched += 1
                    if best is None or (h, n) < best:
                        best = (h, n)
                    if h <= req.target:
                        found = True
                        break
                w.write(encode_msg(Result(
                    msg.job_id, req.mode, best[1], best[0], found=found,
                    searched=searched, chunk_id=msg.chunk_id,
                )))
    except (LspConnectionLost, asyncio.CancelledError):
        pass
    finally:
        await w.close(drain_timeout=0.5)


def test_scrypt_offloaded_verification_never_drops_or_reorders_winner(
    monkeypatch,
):
    """Verification offload e2e (issue satellite): SCRYPT results are
    verified in the executor, so a burst of concurrent chunk Results
    settles asynchronously — the genuine winner must still finish the
    job exactly (never dropped, never outrun by a later claim), and a
    winner-less job must wait for its LAST pending verification before
    reporting the exact fold."""
    from tpuminter import coordinator as coord_mod

    # small scrypt chunks so one job fans into many concurrent
    # verifications (production floor amortizes RPCs; the RACE is what
    # is under test here)
    monkeypatch.setattr(coord_mod, "SCRYPT_MIN_CHUNK", 64)

    hdr = chain.GENESIS_HEADER.pack()
    upper = 511
    table = _scrypt_table(hdr, upper)
    exact_min = min((h, n) for n, h in table.items())

    async def scenario():
        cluster = await Cluster.create(n_miners=0, chunk_size=64)
        actors = [
            asyncio.ensure_future(
                _instant_scrypt_actor(cluster.coord.port, table)
            )
            for _ in range(4)
        ]
        try:
            await asyncio.sleep(0.2)
            # phase 1 — a winner exists (target == the range's true
            # minimum): whatever order the offloaded verifications
            # settle in, the client must get exactly that winner
            req = Request(
                job_id=1, mode=PowMode.SCRYPT, lower=0, upper=upper,
                header=hdr, target=exact_min[0],
            )
            result = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST),
                60.0,
            )
            assert result.found
            assert (result.hash_value, result.nonce) == exact_min
            # phase 2 — no winner (target=1): the job exhausts only
            # after every offloaded verification settles, and the fold
            # is the exact brute-force minimum
            req2 = Request(
                job_id=2, mode=PowMode.SCRYPT, lower=0, upper=upper,
                header=hdr, target=1,
            )
            result2 = await asyncio.wait_for(
                submit("127.0.0.1", cluster.coord.port, req2, params=FAST),
                60.0,
            )
            assert not result2.found
            assert (result2.hash_value, result2.nonce) == exact_min
            assert result2.searched == upper + 1
            # the offload path really ran (not the inline fallback)
            assert cluster.coord.stats["verifications_offloaded"] >= 8
            assert cluster.coord.stats["results_rejected"] == 0
            # nothing left pending: the exhaustion wait drained
            assert not cluster.coord._jobs
        finally:
            for a in actors:
                a.cancel()
            await asyncio.gather(*actors, return_exceptions=True)
            await cluster.close()

    run(scenario(), timeout=120.0)


def test_client_timeout_flag_exits_cleanly(capsys):
    """Satellite (VERDICT r5 next #8): ``--timeout`` bounds the
    reference's block-forever wait — a job nobody mines prints
    ``Timeout`` and exits 1 (the ``Disconnected``-style clean path,
    not a hang or a traceback)."""
    started = threading.Event()
    stop = {}

    def run_coordinator():
        async def main():
            coord = await Coordinator.create(params=FAST)
            stop["loop"] = asyncio.get_running_loop()
            stop["event"] = asyncio.Event()
            stop["port"] = coord.port
            serve = asyncio.ensure_future(coord.serve())
            started.set()
            await stop["event"].wait()
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            await coord.close()

        asyncio.run(main())

    t = threading.Thread(target=run_coordinator, daemon=True)
    t.start()
    assert started.wait(10), "coordinator thread never came up"
    try:
        with pytest.raises(SystemExit) as exc_info:
            client_main([
                f"127.0.0.1:{stop['port']}", "nobody mines this", "99999",
                "--timeout", "0.7",
            ])
        assert exc_info.value.code == 1
        assert "Timeout" in capsys.readouterr().out
    finally:
        stop["loop"].call_soon_threadsafe(stop["event"].set)
        t.join(10)


def test_byzantine_eviction_requeues_and_job_finishes_exact():
    """ISSUE 12 satellite: the byzantine-eviction path end-to-end. A
    worker that answers every dispatch with a forged winner (plausible
    shape, wrong hash) accumulates verifier rejections until eviction
    (``miners_evicted``), its poisoned chunks are requeued, NO forged
    answer ever reaches the client, and an honest miner added after the
    eviction finishes the job with the brute-force-exact minimum."""

    async def scenario():
        from tpuminter.coordinator import MAX_REJECTIONS
        from tpuminter.worker import CpuMiner

        cluster = await Cluster.create(n_miners=0, chunk_size=512)
        try:
            evil = await LspClient.connect(
                "127.0.0.1", cluster.coord.port, FAST
            )
            evil.write(encode_msg(Join(backend="evil", lanes=1)))

            async def forge_forever():
                templates = {}
                try:
                    while True:
                        msg = decode_msg(await evil.read())
                        if isinstance(msg, Setup):
                            templates[msg.request.job_id] = msg.request
                        elif isinstance(msg, Assign):
                            req = templates.get(msg.job_id)
                            if req is None:
                                continue
                            evil.write(encode_msg(Result(
                                msg.job_id, req.mode, nonce=msg.lower,
                                hash_value=(
                                    chain.toy_hash(req.data, msg.upper) ^ 1
                                ),
                                found=True,
                                searched=msg.upper - msg.lower + 1,
                                chunk_id=msg.chunk_id,
                            )))
                except LspConnectionLost:
                    pass  # evicted: exactly the point

            evil_task = asyncio.ensure_future(forge_forever())
            data, upper = b"byzantine-e2e", 4000
            req = Request(job_id=0, mode=PowMode.MIN, lower=0,
                          upper=upper, data=data)
            submit_task = asyncio.ensure_future(
                submit("127.0.0.1", cluster.coord.port, req, params=FAST)
            )
            for _ in range(200):  # ≤ 10 s for the eviction to land
                if cluster.coord.stats["miners_evicted"] >= 1:
                    break
                await asyncio.sleep(0.05)
            stats = cluster.coord.stats
            assert stats["miners_evicted"] == 1
            assert stats["results_rejected"] >= MAX_REJECTIONS
            assert stats["chunks_requeued"] >= 1
            # containment: no forged Result escaped to the client
            assert not submit_task.done()
            await cluster.add_miner(CpuMiner())
            result = await asyncio.wait_for(submit_task, 60.0)
            assert (result.hash_value, result.nonce) == brute_min(
                data, 0, upper
            )
            evil_task.cancel()
            await asyncio.gather(evil_task, return_exceptions=True)
            await evil.close(drain_timeout=0.2)
        finally:
            await cluster.close()

    run(scenario(), timeout=120.0)


def test_loadgen_chaos_smoke_gate(capsys):
    """The tier-1 chaos gate (ISSUE 12 satellite; slow-loris cell added
    by ISSUE 18, clock-skew cell by ISSUE 19): ``--scenario chaos
    --smoke`` runs the netsplit + byzantine + slow_loris + clock_skew
    cells with the full ``chaos_check`` assertions behind rc —
    exactly-once ledger, split brain contained, forged answers
    contained, offenders evicted, lorises reaped, a lying clock
    degrading to delays only — reproducible from ``--seed``."""
    import json as _json

    rc = loadgen.main([
        "--scenario", "chaos", "--smoke", "--seed", "3",
        "--miners", "6", "--clients", "4", "--duration", "1.0", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"chaos smoke gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["seed"] == 3
    assert metrics["cells"] == [
        "netsplit", "byzantine", "slow_loris", "clock_skew",
    ]
    ns = metrics["results"]["netsplit"]
    # the exactly-once ledger held across the split (chaos_check
    # enforces the same behind rc; re-asserted so a loosened check
    # cannot silently drop the criteria)
    assert ns["answered"] > 0
    assert ns["answers_lost"] == 0
    assert ns["answers_duplicated"] == 0
    assert ns["poisoned_answers"] == 0
    assert ns["replicated_records_pre_split"] > 0
    assert ns["old_primary_fenced"] is True
    assert ns["takeover_ms"] <= 20_000
    bz = metrics["results"]["byzantine"]
    assert bz["answered"] > 0
    assert bz["answers_lost"] == 0
    assert bz["answers_duplicated"] == 0
    assert bz["poisoned_answers"] == 0
    assert bz["miners_evicted"] > 0
    assert bz["results_rejected"] > 0
    assert bz["chunks_requeued"] > 0
    sl = metrics["results"]["slow_loris"]
    assert sl["answered"] > 0
    assert sl["answers_lost"] == 0
    assert sl["answers_duplicated"] == 0
    assert sl["lorises_dropped"] > 0
    assert sl["deadline_epochs"] > 0
    cs = metrics["results"]["clock_skew"]
    assert cs["answered"] > 0
    assert cs["answers_lost"] == 0
    assert cs["answers_duplicated"] == 0
    # the clock REALLY lied (drift segments elapsed and diverged) yet
    # every consequence was a delay: refusals honored, nobody evicted
    assert cs["clock_stats"]["segments"] >= 1
    assert cs["clock_stats"]["max_skew_s"] > 0.0
    assert cs["retry_after_honored"] > 0
    assert cs["miners_evicted"] == 0


# ---------------------------------------------------------------------------
# admission & bounded state (ISSUE 13)
# ---------------------------------------------------------------------------

def _trim_oracle(table, cap, ttl, now):
    """Independent mirror of ``Coordinator._trim_winners`` semantics:
    the set of keys the bounds allow evicting. Only durable entries
    with no parked waiters qualify; size excess goes first (insertion
    order), then anything older than ``ttl``."""
    if len(table) <= cap and not ttl:
        return set()
    evictable = [
        k for k, w in table.items() if w.durable and not w.waiters
    ]
    excess = max(0, len(table) - cap)
    evicted = set(evictable[:excess])
    if ttl:
        cutoff = now - ttl
        for k in evictable[excess:]:
            if table[k].ts <= cutoff:
                evicted.add(k)
    return evicted


def test_winner_trim_never_evicts_unacked_seeded():
    """Deterministic mirror of the dedup-table bound (ISSUE 13): over
    400 seeded random winner tables, ``_trim_winners`` evicts exactly
    the oracle's set — and NEVER an un-acknowledged entry (not yet
    durable, or with re-submitters parked on the durability callback),
    whatever the size/age pressure. Evicting one would answer a client
    twice; the cap may be exceeded, exactly-once may not."""
    import random
    import time as _time
    from collections import OrderedDict

    from tpuminter.coordinator import _Winner
    from tpuminter.protocol import PowMode as _PM

    dummy = Result(1, _PM.MIN, nonce=1, hash_value=1, found=True,
                   searched=1, chunk_id=0)
    rng = random.Random(0x15E13)
    for _ in range(400):
        now = _time.time()
        ttl = rng.choice([0.0, 100.0])
        table = OrderedDict()
        for i in range(rng.randrange(0, 24)):
            table[("ck%d" % i, i)] = _Winner(
                dummy,
                durable=rng.random() < 0.6,
                waiters=[7] if rng.random() < 0.3 else [],
                # far from the cutoff on both sides: jitter-proof
                ts=now - (1000.0 if rng.random() < 0.5 else 0.0),
            )
        cap = rng.randrange(0, 16)
        unacked = {
            k for k, w in table.items() if not w.durable or w.waiters
        }
        expected = _trim_oracle(table, cap, ttl, now)

        coord = Coordinator.__new__(Coordinator)
        coord._winners = OrderedDict(table)
        coord._winners_cap = cap
        coord._winners_ttl = ttl
        coord._wall = _time.time  # the clock seam (ISSUE 19)
        coord.stats = {"winners_evicted": 0}
        coord._trim_winners()

        survivors = set(coord._winners)
        assert unacked <= survivors, "un-acked winner evicted"
        assert set(table) - survivors == expected
        assert coord.stats["winners_evicted"] == len(expected)


def test_session_loss_reclaims_per_session_state():
    """Churn's per-session invariant, end-to-end: when a client session
    dies without a goodbye, everything keyed by it is reclaimed — the
    anonymous client's ``@conn:`` quota bucket and jobs go at loss
    detection; the durable client's job rides UNBOUND until
    ``unbound_ttl`` and is then reaped (its identity-keyed bucket
    deliberately persists: a redial must not refill quota)."""

    async def scenario():
        cluster = await Cluster.create(
            n_miners=0, chunk_size=512, quota_rate=50.0,
            unbound_ttl=0.3, stats_interval=0.2,
        )
        coord = cluster.coord
        try:
            anon = await LspClient.connect("127.0.0.1", coord.port, FAST)
            anon.write(encode_msg(Request(
                job_id=1, mode=PowMode.MIN, lower=0, upper=1 << 22,
                data=b"anon-session",
            )))
            durable = await LspClient.connect(
                "127.0.0.1", coord.port, FAST
            )
            durable.write(encode_msg(Request(
                job_id=1, mode=PowMode.MIN, lower=0, upper=1 << 22,
                data=b"durable-session", client_key="reclaim:1",
            )))
            for _ in range(100):  # both jobs admitted and tracked
                if len(coord._jobs) == 2 and len(coord._clients) == 2:
                    break
                await asyncio.sleep(0.05)
            assert len(coord._jobs) == 2
            assert any(k.startswith("@conn:") for k in coord._buckets)
            assert "reclaim:1" in coord._buckets

            # vanish without goodbye: the server only learns via the
            # epoch-liveness horizon, like a kill -9'd client process
            await anon.close(drain_timeout=0.05)
            await durable.close(drain_timeout=0.05)
            for _ in range(200):  # horizon (1.25 s) + unbound_ttl + tick
                if not coord._jobs and not coord._clients:
                    break
                await asyncio.sleep(0.05)
            assert not coord._clients, "per-session table not reclaimed"
            assert not coord._jobs, "UNBOUND residue not reaped"
            assert coord.stats["unbound_reaped"] >= 1
            assert not any(
                k.startswith("@conn:") for k in coord._buckets
            ), "anonymous quota bucket outlived its session"
            # the durable identity's bucket is NOT per-session state
            assert "reclaim:1" in coord._buckets
        finally:
            await cluster.close()

    run(scenario(), timeout=60.0)


def test_loadgen_churn_smoke_gate(capsys):
    """The tier-1 churn gate (ISSUE 13): ``--scenario churn --smoke``
    washes hundreds of short-lived clients (40% abandoning mid-job)
    through a capped coordinator, kill -9s it mid-churn, and gates on
    ``churn_check`` behind rc — every table plateaus at its cap-derived
    bound, ghosts leave zero residue, replay lands within the same
    bounds, and the exactly-once ledger holds — reproducible from
    ``--seed``."""
    import json as _json

    rc = loadgen.main([
        "--scenario", "churn", "--smoke", "--seed", "3", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"churn smoke gate failed: {out}"
    metrics = _json.loads(out.splitlines()[0])
    assert metrics["seed"] == 3
    # re-asserted past churn_check so a loosened check cannot silently
    # drop the criteria (same belt-and-braces as the chaos gate)
    assert metrics["answered"] > 0
    assert metrics["answers_duplicated"] == 0
    assert metrics["unanswered"] == 0
    assert metrics["abandoned"] > 0
    assert metrics["unbound_reaped"] > 0
    assert metrics["jobs_high_water"] <= metrics["max_jobs"]
    assert metrics["sessions_high_water"] <= metrics["session_bound"]
    assert metrics["final_jobs"] == 0
    assert metrics["final_sessions"] == 0
    assert metrics["recovered_jobs"] <= metrics["max_jobs"]
    assert metrics["recovered_winners"] <= metrics["winners_cap"]
