"""JaxMiner tests: the device-backed Worker must be bit-identical to the
CPU reference through the same Miner interface (BASELINE.json:5), on the
CPU backend (tests/conftest.py)."""

import asyncio
import struct

from tpuminter import chain
from tpuminter.client import submit
from tpuminter.jax_worker import JaxMiner
from tpuminter.protocol import PowMode, Request
from tpuminter.worker import CpuMiner

from tests.test_e2e import FAST, Cluster, brute_min, run


def drain(gen):
    for item in gen:
        if item is not None:
            return item
    raise AssertionError("miner generator ended without a Result")


def test_min_mode_matches_brute_force():
    miner = JaxMiner(batch=512)
    req = Request(job_id=1, mode=PowMode.MIN, lower=100, upper=3000,
                  data=b"jax parity")
    result = drain(miner.mine(req))
    want_hash, want_nonce = brute_min(b"jax parity", 100, 3000)
    assert (result.hash_value, result.nonce) == (want_hash, want_nonce)
    assert result.searched == 2901


def test_min_mode_beyond_32_bit_nonces():
    miner = JaxMiner(batch=512)
    lower = (1 << 33) + 5
    req = Request(job_id=1, mode=PowMode.MIN, lower=lower, upper=lower + 999,
                  data=b"wide nonces")
    result = drain(miner.mine(req))
    want = brute_min(b"wide nonces", lower, lower + 999)
    assert (result.hash_value, result.nonce) == want


def test_min_mode_at_top_of_64_bit_space():
    """Regression: the ragged final batch at the 2^64 ceiling must pad
    with `upper`, not wrap modulo 64 bits into out-of-range nonces."""
    miner = JaxMiner(batch=512)
    upper = 2**64 - 1
    lower = upper - 99
    req = Request(job_id=1, mode=PowMode.MIN, lower=lower, upper=upper,
                  data=b"ceiling")
    result = drain(miner.mine(req))
    want = brute_min(b"ceiling", lower, upper)
    assert (result.hash_value, result.nonce) == want
    assert lower <= result.nonce <= upper


def test_target_mode_finds_genesis():
    miner = JaxMiner(batch=512)
    n = chain.GENESIS_HEADER.nonce
    req = Request(
        job_id=1, mode=PowMode.TARGET, lower=n - 600, upper=n + 600,
        header=chain.GENESIS_HEADER.pack(),
        target=chain.bits_to_target(0x1D00FFFF),
    )
    result = drain(miner.mine(req))
    assert result.found
    assert result.nonce == n
    assert result.hash_value == chain.GENESIS_HEADER.block_hash_int()
    # searched counts only up to the hit
    assert result.searched == (n - (n - 600)) + 1


def test_target_mode_exhausted_matches_cpu_miner():
    req = Request(
        job_id=1, mode=PowMode.TARGET, lower=0, upper=2047,
        header=chain.GENESIS_HEADER.pack(),
        target=chain.bits_to_target(0x1D00FFFF),
    )
    jax_result = drain(JaxMiner(batch=512).mine(req))
    cpu_result = drain(CpuMiner().mine(req))
    assert not jax_result.found
    assert (jax_result.hash_value, jax_result.nonce) == (
        cpu_result.hash_value, cpu_result.nonce,
    )
    assert jax_result.searched == 2048


def test_mixed_backend_cluster():
    """CpuMiner and JaxMiner mining the same job side by side — the
    heterogeneous-worker story the lane-scaled chunking exists for."""

    async def scenario():
        cluster = await Cluster.create(n_miners=1, chunk_size=1024)
        try:
            await cluster.add_miner(JaxMiner(batch=512, lanes=2))
            data = b"mixed fleet"
            req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=30_000,
                          data=data)
            result = await submit("127.0.0.1", cluster.coord.port, req,
                                  params=FAST)
            assert (result.hash_value, result.nonce) == brute_min(data, 0, 30_000)
        finally:
            await cluster.close()

    run(scenario())


def test_profiled_miner_writes_trace(tmp_path):
    """--profile observability (VERDICT r2 #7): the wrapper records a
    jax.profiler trace of the first chunk and passes results through
    unchanged."""
    import os

    from tpuminter.jax_worker import JaxMiner
    from tpuminter.protocol import PowMode, Request
    from tpuminter.worker import ProfiledMiner

    inner = JaxMiner(batch=1 << 12)
    miner = ProfiledMiner(inner, str(tmp_path))
    assert (miner.backend, miner.lanes) == (inner.backend, inner.lanes)
    # enough batches that the steady-state window (steps 1-3) exists
    req = Request(job_id=1, mode=PowMode.MIN, lower=0, upper=24000, data=b"p")
    result = None
    for item in miner.mine(req):
        if item is not None:
            result = item
    assert result is not None and result.found
    # a trace landed on disk (plugins/profile/<run>/...)
    def trace_files():
        return sorted(
            os.path.join(root, f)
            for root, _, files in os.walk(tmp_path) for f in files
        )

    produced = trace_files()
    assert produced, "no profiler trace files written"
    # second chunk is NOT traced (single-shot by design): no new files
    for item in miner.mine(req):
        pass
    assert trace_files() == produced
