"""Multi-process sharded coordinator (ISSUE 19): the seam frame codec's
round-trip/rejection properties, the config guards, and the 2-process
end-to-end gates.

The drills are the tier-1 acceptance the issue names, run on real OS
processes behind ONE UDP port: zero duplicate answers and zero lost
miners across the process seam, a kill -9 + recovery whose re-submitted
LIVE job lands on a FOREIGN shard process and settles exactly once
through the cross-shard rebind registry, and one tenant's token bucket
enforced fleet-wide while its submissions alternate across processes.
On this one-core image the gates are deterministic invariants (the
procs-throughput *curve* is bench.py's job, pre-staged for multi-core
hosts)."""

import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import loadgen  # noqa: E402  (scripts/ is not a package)

from tpuminter.multiproc import MultiProcCoordinator  # noqa: E402
from tpuminter.protocol import (  # noqa: E402
    ProtocolError,
    SEAM_CKEY_MAX,
    decode_seam,
    encode_seam_answer,
    encode_seam_bind,
    encode_seam_fwd,
    encode_seam_quota,
    encode_seam_rebind,
)

from tests.test_e2e import run  # noqa: E402


# ---------------------------------------------------------------------------
# the seam frame codec (pure)
# ---------------------------------------------------------------------------

def test_seam_frames_round_trip():
    """Every seam dialect survives encode → decode bit-exact, including
    the miss flag and a ckey at the size limit."""
    assert decode_seam(
        encode_seam_fwd(("10.1.2.3", 65535), b"\x01payload")
    ) == ("fwd", ("10.1.2.3", 65535), b"\x01payload")

    big_ckey = "k" * SEAM_CKEY_MAX
    assert decode_seam(encode_seam_bind(7, big_ckey, 2**63)) == (
        "bind", 7, big_ckey, 2**63,
    )
    assert decode_seam(
        encode_seam_rebind(1, 0xDEADBEEF, "tenant-a", 42)
    ) == ("rebind", 1, 0xDEADBEEF, "tenant-a", 42)
    assert decode_seam(
        encode_seam_answer(0xDEADBEEF, 42, b"\x7b\x7d")
    ) == ("answer", False, 0xDEADBEEF, 42, b"\x7b\x7d")
    assert decode_seam(
        encode_seam_answer(3, 9, b"", miss=True)
    ) == ("answer", True, 3, 9, b"")
    assert decode_seam(encode_seam_quota(0, "tenant-b", 10**9)) == (
        "quota", 0, "tenant-b", 10**9,
    )


def test_seam_frames_reject_corruption_and_bad_fields():
    """The seam is loss-tolerant, so the decoder must refuse (never
    misread) every damaged frame: flipped bits, truncation, unknown
    tags, and out-of-contract fields at encode time."""
    frame = bytearray(encode_seam_rebind(0, 11, "tenant", 5))
    frame[len(frame) // 2] ^= 0x40
    with pytest.raises(ProtocolError):
        decode_seam(bytes(frame))  # CRC catches the flip
    good = encode_seam_bind(1, "k", 2)
    for cut in (0, 1, len(good) - 1):
        with pytest.raises(ProtocolError):
            decode_seam(good[:cut])
    with pytest.raises(ProtocolError):
        decode_seam(b"\xee" + good[1:])  # unknown tag

    with pytest.raises(ProtocolError):
        encode_seam_bind(1, "", 2)  # empty ckey
    with pytest.raises(ProtocolError):
        encode_seam_bind(1, "k" * (SEAM_CKEY_MAX + 1), 2)
    with pytest.raises(ProtocolError):
        encode_seam_answer(1, 2, b"data", miss=True)  # miss carries none
    with pytest.raises(ProtocolError):
        encode_seam_fwd(("::1", 9), b"")  # IPv4 only on the seam
    with pytest.raises(ProtocolError):
        encode_seam_fwd(("127.0.0.1", 1 << 16), b"")
    with pytest.raises(ProtocolError):
        encode_seam_rebind(256, 1, "k", 1)  # origin is one byte


# ---------------------------------------------------------------------------
# config guards
# ---------------------------------------------------------------------------

def test_multiproc_rejects_bad_configs():
    async def scenario():
        with pytest.raises(ValueError):
            await MultiProcCoordinator.create(procs=0)
        # process mode owns the whole port: in-process loops/threads
        # on top of it would double-shard the same peers
        with pytest.raises(ValueError):
            await loadgen.make_coordinator(procs=2, loops=2)
        with pytest.raises(ValueError):
            await loadgen.make_coordinator(procs=2, threaded=True)

    run(scenario())


# ---------------------------------------------------------------------------
# the 2-process gates (ISSUE 19 acceptance)
# ---------------------------------------------------------------------------

def test_two_proc_smoke_rebind_and_quota_drills():
    """The tier-1 2-process gate: a fleet-8 burst across 2 shard
    processes sustains with zero duplicate answers, zero lost miners,
    and both processes answering stats over the control seam; then the
    kill -9 rebind drill settles its cross-process re-submit exactly
    once THROUGH the rebind registry (honored >= 1 proves the answer
    crossed the seam rather than being re-mined); then the shared-quota
    drill holds one tenant to its fleet-wide budget while alternating
    shards."""
    metrics = run(loadgen.run_multiproc(8, 4, 1.2, procs=2), timeout=180.0)
    assert loadgen.multiproc_check(metrics) == [], metrics
    assert metrics["procs"] == 2
    assert metrics["dup_answers"] == 0
    assert metrics["miners_lost"] == 0
    assert metrics["shards_replied"] == 2
    # the kernel steers on this image's cBPF; if attach ever regresses
    # to the userspace fallback the seam must still deliver (fwd path),
    # so steer_kernel is recorded but not load-bearing for correctness
    assert metrics["steer_kernel"] in (True, False)
    assert metrics["rebind_settled"] == 1
    assert metrics["rebind_seam_honored"] >= 1
    assert metrics["quota_admitted"] <= metrics["quota_burst"] + 1
    assert metrics["quota_foreign_debits"] > 0, (
        "quota drill alternated shards but no bucket ever saw a "
        "foreign debit — the gossip seam is dark"
    )


def test_one_proc_mode_is_the_degenerate_case():
    """procs=1 must behave exactly like a plain coordinator behind the
    process supervisor — no steering (one socket), no drills needed,
    full throughput path intact. This is the A/B baseline bench.py
    measures seam overhead against."""
    metrics = run(
        loadgen.run_multiproc(6, 2, 0.9, procs=1, drills=False),
        timeout=120.0,
    )
    assert metrics["procs"] == 1
    assert metrics["results_per_s"] > 0
    assert metrics["dup_answers"] == 0
    assert metrics["miners_lost"] == 0
    assert metrics["shards_replied"] == 1
    assert metrics["steer_kernel"] is False
