// Native CPU mining core (SURVEY.md §2 #9 parity): the reference's CPU
// worker is a *compiled* Go hot loop (~MH/s-scale); the Python CpuMiner
// reproduces its semantics but not its speed class. This translation
// unit provides the compiled equivalent — a double-SHA-256 nonce-range
// search with first-winner early exit and exact min tracking — exposed
// through a minimal C ABI that tpuminter/native_worker.py binds with
// ctypes (no pybind11 in this image; see Makefile).
//
// Semantics are pinned bit-for-bit to tpuminter.chain/CpuMiner by
// tests/test_native.py: same first-winner rule, same lexicographic
// 256-bit min fold, same searched accounting.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

constexpr uint32_t H0[8] = {
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

inline void compress(uint32_t state[8], const uint32_t w_in[16]) {
  uint32_t w[64];
  std::memcpy(w, w_in, 16 * sizeof(uint32_t));
  for (int i = 16; i < 64; ++i) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; ++i) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = g ^ (e & (f ^ g));
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (c & (a ^ b));
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

inline uint32_t load_be(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// hash VALUE words, most-significant first: Bitcoin reads the 32-byte
// digest as a little-endian integer, so value word j is the byteswap of
// digest word 7-j (same convention as ops.sha256.hash_words_be).
inline uint32_t bswap(uint32_t x) { return __builtin_bswap32(x); }

// lexicographic compare of two 8-word msb-first values: a < b
inline bool lt256(const uint32_t a[8], const uint32_t b[8]) {
  for (int i = 0; i < 8; ++i) {
    if (a[i] != b[i]) return a[i] < b[i];
  }
  return false;
}

}  // namespace

extern "C" {

// Search [lower, upper] (inclusive, u32 nonces) of an 80-byte header
// whose first 76 bytes are `header76` for the FIRST nonce whose
// double-SHA-256 hash value is <= target (8 msb-first u32 words),
// tracking the exact running minimum.
//
// Returns 1 if a winner was found, else 0. Outputs:
//   out_nonce      — winning nonce, or the argmin nonce when none won
//   out_hash[8]    — that nonce's hash value words (msb-first)
//   out_searched   — nonces examined (early exit counts its prefix)
//
// The midstate of the first 64 header bytes is compressed once; per
// nonce only the 16-byte tail block + the second hash run (the same
// specialization the device templates use, ops/sha256.py).
int sha256d_search(const uint8_t* header76, uint32_t lower, uint32_t upper,
                   const uint32_t* target, uint32_t* out_nonce,
                   uint32_t* out_hash, uint64_t* out_searched) {
  uint32_t mid[8];
  std::memcpy(mid, H0, sizeof(mid));
  uint32_t w[16];
  for (int i = 0; i < 16; ++i) w[i] = load_be(header76 + 4 * i);
  compress(mid, w);

  // constant part of the tail block: bytes 64..76 + padding for 80 bytes
  uint32_t tail[16] = {0};
  tail[0] = load_be(header76 + 64);
  tail[1] = load_be(header76 + 68);
  tail[2] = load_be(header76 + 72);
  // tail[3] = nonce (little-endian bytes read big-endian = bswap)
  tail[4] = 0x80000000u;
  tail[15] = 640;

  uint32_t second[16] = {0};
  second[8] = 0x80000000u;
  second[15] = 256;

  uint32_t best[8];
  std::memset(best, 0xFF, sizeof(best));
  uint32_t best_nonce = lower;
  uint64_t searched = 0;

  for (uint64_t n = lower; n <= upper; ++n) {
    uint32_t st[8];
    std::memcpy(st, mid, sizeof(st));
    tail[3] = bswap(uint32_t(n));
    compress(st, tail);
    std::memcpy(second, st, 8 * sizeof(uint32_t));
    uint32_t st2[8];
    std::memcpy(st2, H0, sizeof(st2));
    compress(st2, second);
    uint32_t hv[8];
    for (int i = 0; i < 8; ++i) hv[i] = bswap(st2[7 - i]);
    ++searched;
    if (lt256(hv, best)) {
      std::memcpy(best, hv, sizeof(best));
      best_nonce = uint32_t(n);
      if (!lt256(target, hv)) {  // hv <= target: first winner ends it
        *out_nonce = uint32_t(n);
        std::memcpy(out_hash, hv, sizeof(best));
        *out_searched = searched;
        return 1;
      }
    }
  }
  *out_nonce = best_nonce;
  std::memcpy(out_hash, best, sizeof(best));
  *out_searched = searched;
  return 0;
}

// Batch double-SHA-256 of `count` independent (header76, nonce) pairs:
// the coordinator-side verification entry point. Unlike sha256d_search
// (one header, many nonces) each item here may be a different header —
// a verification burst mixes jobs and rolled extranonce segments — so
// the midstate is computed per item: 4 compressions each, the same
// work a worker's claim cost to make honest.
//
// headers76: count × 76 bytes, packed back to back.
// out_hash:  count × 8 msb-first u32 hash VALUE words (same convention
//            as sha256d_search's out_hash).
void sha256d_hash_batch(const uint8_t* headers76, const uint32_t* nonces,
                        uint64_t count, uint32_t* out_hash) {
  for (uint64_t i = 0; i < count; ++i) {
    const uint8_t* hdr = headers76 + 76 * i;
    uint32_t mid[8];
    std::memcpy(mid, H0, sizeof(mid));
    uint32_t w[16];
    for (int j = 0; j < 16; ++j) w[j] = load_be(hdr + 4 * j);
    compress(mid, w);
    uint32_t tail[16] = {0};
    tail[0] = load_be(hdr + 64);
    tail[1] = load_be(hdr + 68);
    tail[2] = load_be(hdr + 72);
    tail[3] = bswap(nonces[i]);
    tail[4] = 0x80000000u;
    tail[15] = 640;
    compress(mid, tail);
    uint32_t second[16] = {0};
    std::memcpy(second, mid, 8 * sizeof(uint32_t));
    second[8] = 0x80000000u;
    second[15] = 256;
    uint32_t st2[8];
    std::memcpy(st2, H0, sizeof(st2));
    compress(st2, second);
    for (int j = 0; j < 8; ++j) out_hash[8 * i + j] = bswap(st2[7 - j]);
  }
}

// Toy dialect (reference parity): minimize the 64-bit fold (first 8
// digest bytes, big-endian) of SHA-256(data ‖ nonce_be8) over
// [lower, upper]. Writes the argmin nonce and fold value.
void toy_min_search(const uint8_t* data, uint64_t len, uint64_t lower,
                    uint64_t upper, uint64_t* out_nonce, uint64_t* out_fold) {
  // message = data ‖ 8 nonce bytes; full padding recomputed per nonce is
  // wasteful, so precompute the midstate of all whole 64-byte blocks
  // that contain no nonce bytes.
  uint64_t msg_len = len + 8;
  uint64_t n_whole = len / 64;  // blocks fully before the nonce bytes? only
  // blocks entirely within data[0 : len - (len % 64)] are constant iff
  // they end at or before len rounded down AND before the nonce start.
  // The nonce begins at byte `len`, so all blocks ending <= len are
  // constant only when 64*k <= len. (len % 64 == 0 edge included.)
  uint32_t mid[8];
  std::memcpy(mid, H0, sizeof(mid));
  uint64_t const_bytes = n_whole * 64;
  uint32_t w[16];
  for (uint64_t b = 0; b < n_whole; ++b) {
    for (int i = 0; i < 16; ++i) w[i] = load_be(data + b * 64 + 4 * i);
    compress(mid, w);
  }
  // assemble the variable tail (data remainder ‖ nonce ‖ pad ‖ len)
  uint64_t rem = len - const_bytes;
  uint64_t tail_len = msg_len - const_bytes;     // bytes of real message
  uint64_t padded = ((tail_len + 8) / 64 + 1) * 64;  // 0x80 + u64 length
  uint8_t buf[192];  // rem <= 63, +8 nonce, +pad: <= 135 < 192
  uint64_t best_fold = ~0ull;
  uint64_t best_nonce = lower;
  for (uint64_t n = lower;; ++n) {
    std::memset(buf, 0, sizeof(buf));
    std::memcpy(buf, data + const_bytes, rem);
    for (int i = 0; i < 8; ++i) buf[rem + i] = uint8_t(n >> (56 - 8 * i));
    buf[tail_len] = 0x80;
    uint64_t bits = msg_len * 8;
    for (int i = 0; i < 8; ++i)
      buf[padded - 8 + i] = uint8_t(bits >> (56 - 8 * i));
    uint32_t st[8];
    std::memcpy(st, mid, sizeof(st));
    for (uint64_t b = 0; b < padded / 64; ++b) {
      for (int i = 0; i < 16; ++i) w[i] = load_be(buf + b * 64 + 4 * i);
      compress(st, w);
    }
    uint64_t fold = (uint64_t(st[0]) << 32) | st[1];
    if (fold < best_fold) {
      best_fold = fold;
      best_nonce = n;
    }
    if (n == upper) break;  // upper may be UINT64_MAX: no n<=upper loop
  }
  *out_nonce = best_nonce;
  *out_fold = best_fold;
}

}  // extern "C"
